"""Compile-wall management (ROADMAP item 4; docs/Compile-Cache.md):

- shared shape-bucketing policy units (utils/shapes.py);
- persistent-cache bring-up respects a pre-configured directory and
  parameterizes the persistence thresholds (the old helper clobbered
  both);
- the leaf-budget bucket: num_leaves 31/40/63 train through ONE padded
  L=64 grower trace with models byte-identical to the unbucketed
  per-shape path, across strict/batched growth and bagging/GOSS;
- compile accounting surfaces through Booster.telemetry_snapshot()
  and the serve /metrics snapshot.

The cross-process pieces (second-process warm start, the retrace-
budget lint subprocess, dp parity) live in tests/test_zretrace.py —
they spawn fresh interpreters and run late in the suite.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils import shapes
from lightgbm_tpu.utils.compile_cache import (compile_stats,
                                              enable_persistent_cache,
                                              trace_counts)


def _tree_text(model_str: str) -> str:
    """Model text minus the parameters section (which records the
    trace_buckets flag itself and therefore legitimately differs)."""
    return model_str.split("end of parameters", 1)[-1]


def _sweep_params(nl, tb, **over):
    p = {"objective": "binary", "num_leaves": nl, "verbosity": 0,
         "min_data_in_leaf": 5, "max_bin": 15, "tpu_learner": "masked",
         "fused_chunk": 0, "trace_buckets": tb}
    p.update(over)
    return p


@pytest.fixture(scope="module")
def sweep_data():
    rs = np.random.RandomState(7)
    x = rs.randn(700, 10)
    y = (x[:, 0] * 1.5 - x[:, 1] + 0.4 * rs.randn(700) > 0)
    return x, y.astype(np.float32)


def _train_text(x, y, nl, tb, rounds=3, **over):
    p = _sweep_params(nl, tb, **over)
    ds = lgb.Dataset(x, label=y, params=p)
    return _tree_text(lgb.train(p, ds, num_boost_round=rounds)
                      .model_to_string())


class TestShapes:
    def test_round_up_pow2(self):
        assert [shapes.round_up_pow2(v) for v in (1, 2, 3, 17, 64, 65)] \
            == [1, 2, 4, 32, 64, 128]

    def test_bucket_rows_floor_and_cap(self):
        assert shapes.bucket_rows(3) == 16
        assert shapes.bucket_rows(17) == 32
        assert shapes.bucket_rows(300, min_bucket=256) == 512
        assert shapes.bucket_rows(5000, cap=1024) == 1024

    def test_bucket_leaves(self):
        # the headline consolidation: the common 31..63 budgets share
        # one bucket; larger budgets pow2 up
        assert [shapes.bucket_leaves(v) for v in (2, 31, 40, 63, 64)] \
            == [64, 64, 64, 64, 64]
        assert shapes.bucket_leaves(127) == 128
        assert shapes.bucket_leaves(255) == 256

    def test_snap_split_batch(self):
        # ISSUE 15 extended the shipped set to {1, 8, 16, 32, 64}: an
        # off-set request still rounds UP within the set, and values
        # past the widest snap down to it
        assert [shapes.snap_split_batch(v) for v in (0, 1, 2, 4, 8, 9,
                                                     16, 40, 64, 99)] \
            == [0, 1, 8, 8, 8, 16, 16, 64, 64, 64]

    def test_serve_engine_uses_shared_policy(self, sweep_data):
        from lightgbm_tpu.serve.engine import PredictorEngine
        x, y = sweep_data
        p = _sweep_params(7, True)
        ds = lgb.Dataset(x, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=2)
        eng = PredictorEngine.from_booster(bst, max_batch=64,
                                           min_bucket=16)
        assert eng._bucket(3) == shapes.bucket_rows(3, 16, 64) == 16
        assert eng._bucket(500) == shapes.bucket_rows(500, 16, 64) == 64


class TestPersistentCacheConfig:
    def test_respects_preconfigured_dir(self, tmp_path):
        """The old enable unconditionally overwrote
        jax_compilation_cache_dir; a pre-set dir must now win unless an
        explicit cache_dir is passed."""
        import jax
        before = jax.config.jax_compilation_cache_dir
        try:
            mine = str(tmp_path / "pre")
            jax.config.update("jax_compilation_cache_dir", mine)
            assert enable_persistent_cache() == mine
            assert jax.config.jax_compilation_cache_dir == mine
            explicit = str(tmp_path / "explicit")
            assert enable_persistent_cache(cache_dir=explicit) == explicit
            assert jax.config.jax_compilation_cache_dir == explicit
        finally:
            jax.config.update("jax_compilation_cache_dir", before)

    def test_thresholds_are_parameters(self, tmp_path):
        import jax
        before = jax.config.jax_compilation_cache_dir
        try:
            enable_persistent_cache(min_compile_secs=1.25,
                                    cache_dir=str(tmp_path / "t"),
                                    min_entry_bytes=123)
            assert jax.config.jax_persistent_cache_min_compile_time_secs \
                == 1.25
            assert jax.config.jax_persistent_cache_min_entry_size_bytes \
                == 123
        finally:
            jax.config.update("jax_compilation_cache_dir", before)
            enable_persistent_cache()     # restore conftest thresholds

    def test_config_rejects_negative_thresholds(self):
        with pytest.raises(ValueError):
            lgb.Config({"compile_cache_min_compile_s": -1.0})
        with pytest.raises(ValueError):
            lgb.Config({"compile_cache_min_entry_bytes": -1})


class TestLeafBucketing:
    def test_sweep_shares_one_trace_and_is_byte_identical(self,
                                                          sweep_data):
        """num_leaves 31/40/63 (strict growth) compile exactly one
        padded L=64 grower trace, and every model matches the
        unbucketed per-shape path byte-for-byte."""
        from lightgbm_tpu.grower import grower_trace_count
        x, y = sweep_data
        t0 = grower_trace_count()
        bucketed = {nl: _train_text(x, y, nl, True) for nl in (31, 40, 63)}
        # <= 1, not == 1: an earlier test in this module may already
        # have traced the bucket's shared grower (the memo working
        # across tests); the strict ==1 pin for a FRESH process is
        # tools/check_retraces.py's leaf_sweep scenario
        assert grower_trace_count() - t0 <= 1
        for nl in (31, 40, 63):
            assert bucketed[nl] == _train_text(x, y, nl, False), \
                f"bucketed num_leaves={nl} diverged from exact path"

    @pytest.mark.parametrize("extra", [
        {"bagging_fraction": 0.7, "bagging_freq": 1},
        {"data_sample_strategy": "goss"},
        {"split_batch": 8},
    ], ids=["bagging", "goss", "batched"])
    def test_sampling_and_batched_parity(self, sweep_data, extra):
        x, y = sweep_data
        assert _train_text(x, y, 40, True, **extra) \
            == _train_text(x, y, 40, False, **extra)

    def test_sampling_reuses_the_sweep_trace(self, sweep_data):
        """Bagging/GOSS change histogram VALUES, never shapes: the
        process-level grower memo must serve them from the already-
        traced config (zero fresh grower traces)."""
        from lightgbm_tpu.grower import grower_trace_count
        x, y = sweep_data
        _train_text(x, y, 40, True)          # ensure the config is traced
        t0 = grower_trace_count()
        _train_text(x, y, 40, True, bagging_fraction=0.7, bagging_freq=1)
        _train_text(x, y, 40, True, data_sample_strategy="goss")
        assert grower_trace_count() - t0 == 0

    def test_explicit_split_batch_snaps_to_shipped_set(self, sweep_data):
        x, y = sweep_data
        p = _sweep_params(40, True, split_batch=4)
        ds = lgb.Dataset(x, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=1)
        assert bst._model._split_batch == 8
        p = _sweep_params(40, False, split_batch=4)
        ds = lgb.Dataset(x, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=1)
        assert bst._model._split_batch == 4    # escape hatch honored

    def test_valid_row_bucketing_metrics_identical(self, sweep_data):
        import lightgbm_tpu.callback as cb
        x, y = sweep_data
        recs = []
        for tb in (True, False):
            p = _sweep_params(15, tb, metric=["binary_logloss"])
            ds = lgb.Dataset(x, label=y, params=p)
            v1 = lgb.Dataset(x[:200], label=y[:200], params=p,
                             reference=ds)
            v2 = lgb.Dataset(x[200:430], label=y[200:430], params=p,
                             reference=ds)
            rec = {}
            lgb.train(p, ds, num_boost_round=3, valid_sets=[v1, v2],
                      callbacks=[cb.record_evaluation(rec)])
            recs.append(rec)
        assert recs[0] == recs[1]


class TestCompileTelemetry:
    def test_booster_snapshot_has_compile_keys(self, sweep_data):
        x, y = sweep_data
        p = _sweep_params(7, True)
        ds = lgb.Dataset(x, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=1)
        snap = bst.telemetry_snapshot()
        for k in ("compile.count", "compile.seconds",
                  "compile.cache_hits", "compile.cache_misses",
                  "compile.traces"):
            assert k in snap
        # the suite has been compiling all along — the process counters
        # must have seen it
        assert snap["compile.count"] > 0
        assert snap["compile.traces"] > 0

    def test_serve_metrics_snapshot_has_compile_keys(self, sweep_data):
        from lightgbm_tpu.serve.server import Server
        x, y = sweep_data
        p = _sweep_params(7, True)
        ds = lgb.Dataset(x, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=1)
        srv = Server(params=p, booster=bst)
        try:
            srv.predict(x[:8])
            snap = srv.metrics_snapshot()
            for k in ("compile.count", "compile.cache_hits",
                      "compile.seconds", "compile.traces"):
                assert k in snap
            assert isinstance(snap["compile.traces"], dict)
        finally:
            srv.close()

    def test_trace_counters_monotone_and_named(self):
        tc = trace_counts()
        assert tc.get("grower", 0) >= 1        # this suite trained
        cs = compile_stats()
        assert set(cs) == {"count", "seconds", "cache_hits",
                           "cache_misses"}
