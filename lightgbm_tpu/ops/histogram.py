"""Histogram construction: the hot kernel of GBDT training.

Replaces the reference's histogram kernels — CPU
``DenseBin::ConstructHistogram`` (/root/reference/src/io/dense_bin.hpp),
CUDA ``CUDAConstructHistogramDenseKernel``
(/root/reference/src/treelearner/cuda/cuda_histogram_constructor.cu:18-70,
shared-memory atomicAdd per (bin, grad/hess)) — with a TPU-native
formulation: scatter-add has no fast TPU lowering, so the histogram is
computed as a **one-hot contraction on the MXU**:

    hist[f*B + b, c] = sum_n (binned[n, f] == b) * vals[n, c]

i.e. a single ``[F*B, n] @ [n, C]`` matmul per row-block, accumulated over
blocks with ``lax.scan``.  The one-hot operand is generated on the fly
(iota-compare) and fused by XLA into the matmul operand load, so HBM traffic
stays at the binned-matrix + vals bytes.  Channels C = (grad, hess, count).

All features share a uniform padded bin axis ``B`` (= dataset max_bin) so
shapes are static; per-feature valid-bin masking happens in the split scan.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


# Cap on the row-block (lax.scan chunk) size for the histogram pass.
# Measured on TPU v5e (tools/bench_hist.py, 1M x 28 x 63 bins): with the
# [C, rows] x [rows, F*B] orientation below, 8192-row blocks run ~1.8x
# faster than VMEM-sized 888-row blocks — XLA tiles the one-hot
# internally, so second-guessing VMEM only shrank the matmuls.
HIST_BLOCK_ROWS = 8192
# ...but the one-hot intermediate is block*F*Bp*itemsize bytes: keep it
# bounded so wide/high-bin datasets (e.g. Bosch-like 968 features x 256
# bins) don't materialize multi-GB scan blocks in HBM.
HIST_ONEHOT_BUDGET = 64 * 1024 * 1024


def hist_block_rows(num_features: int, padded_bins: int,
                    itemsize: int = 4, channels: int = 3) -> int:
    """Row-block size bounded by the one-hot intermediate's byte
    budget.  ``itemsize`` is the accumuland (vals) element width — the
    one-hot operand is generated at the SAME width so the dot's operand
    dtypes match, so int8-packed passes (quant_train, ops/quantize.py)
    get proportionally larger blocks than the f32 default.

    ``channels``: the slot-expanded (and lane-padded) accumuland width
    C = cv·K of the multi-leaf contraction.  Past the shipped ceiling
    (C = 48, K = 16) the budget must account the C·K expansion the old
    feature-only formula ignored — at K=64 on a wide dataset the scan
    working set silently overshot ``HIST_ONEHOT_BUDGET``:

    - the ``[C, F·Bp]`` ACCUMULATOR carry (4-byte lanes) is resident
      for the whole scan regardless of block size, so it is subtracted
      from the budget first (a carry alone past the budget floors the
      block at 8 rows rather than pretending the budget holds);
    - the per-block ``vals ⊗ onehot(slot)`` product adds
      ``block·C·itemsize`` alongside the one-hot's ``block·F·Bp``.

    At or below the shipped widths both terms are EXCLUDED so the
    regression-pinned block shapes (and therefore the f32 accumulation
    order — histograms are byte-identical only for identical block
    partitions) of split_batch ∈ {1, 8, 16} stay exactly as before."""
    per_row = num_features * padded_bins * int(itemsize)
    budget = HIST_ONEHOT_BUDGET
    from ..utils.shapes import HIST_CHANNEL_EXACT_MAX
    if int(channels) > HIST_CHANNEL_EXACT_MAX:
        per_row += int(channels) * int(itemsize)
        budget -= int(channels) * num_features * padded_bins * 4
    blk = max(budget, 0) // max(per_row, 1)
    return max(8, min(HIST_BLOCK_ROWS, blk // 8 * 8))


def pad_feature_axis(h: jax.Array, total: int) -> jax.Array:
    """Zero-pad the leading (feature/group) axis of a histogram to
    ``total`` rows.  The owner-shard reduce-scatter
    (parallel/data_parallel.py) needs the histogram's chunk axis to
    divide evenly over the mesh; zero rows reduce to zero and are never
    scanned (their scan slots carry a False feature mask)."""
    pad = total - h.shape[0]
    if pad <= 0:
        return h
    return jnp.pad(h, ((0, pad),) + ((0, 0),) * (h.ndim - 1))


def compute_histogram(binned: jax.Array, vals: jax.Array, *, num_bins: int,
                      block_rows: int = 0, slot: Optional[jax.Array] = None,
                      num_slots: int = 1) -> jax.Array:
    """hist[f, b, c] = sum over rows n of (binned[n,f]==b) * vals[n,c].

    binned: [N, F] integer bins (uint8/uint16/int32)
    vals:   [N, C] float32 per-row accumulands (grad, hess, count-weight);
            rows outside the target leaf / bag must already be zeroed.
            int8/int16 vals (quantized training, ops/quantize.py) take
            the integer contraction: the one-hot operand is generated at
            the vals dtype and the dot accumulates **exact int32**, so
            the returned histogram is int32 and cross-shard reductions
            of it are bitwise order-independent.
    returns [F, num_bins, C] float32 (int32 for integer vals) — with
    ``slot`` set, C becomes ``C * num_slots``.

    slot/num_slots: per-row slot id in [0, num_slots) or negative for
    "no slot" (row contributes nothing).  The per-slot one-hot expansion
    ``vals ⊗ onehot(slot)`` is generated INSIDE the row-block scan, so
    the multi-leaf batched grower never materializes the [N, C*K]
    operand in HBM (at 10M rows x K=8 that buffer alone would be ~1 GB).

    Backend: the XLA one-hot-matmul scan below on every platform.  A
    hand-written Pallas kernel was built and measured SLOWER on TPU v5e
    (8.2 vs 4.7 ms/pass at 1M x 28 x 64 bins: XLA fuses the one-hot
    generation into the dot's operand load better than the explicit
    kernel, and the matmul already sits at the M-axis sublane ceiling
    PROFILE.md documents), so it was removed rather than shipped as dead
    code; the batched multi-leaf contraction (grower.py split_batch) is
    the path past that ceiling.
    """
    return _compute_histogram_matmul(binned, vals, num_bins=num_bins,
                                     block_rows=block_rows, slot=slot,
                                     num_slots=num_slots)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "block_rows", "num_slots"))
def _compute_histogram_matmul(binned: jax.Array, vals: jax.Array, *,
                              num_bins: int, block_rows: int = 0,
                              slot: Optional[jax.Array] = None,
                              num_slots: int = 1) -> jax.Array:
    n, f = binned.shape
    c = vals.shape[1] * (num_slots if slot is not None else 1)
    # wide multi-leaf contractions (split_batch K ∈ {32, 64} → C = 3K
    # ∈ {96, 192}) pad the channel axis to MXU lane multiples of 128
    # (utils/shapes.bucket_channels) so the [block, C] accumuland
    # operand fills whole 128-lane tiles; the pad columns belong to
    # slots no row carries (exact zeros) and are sliced off below.
    # Shipped widths (C <= 48) keep their exact shapes.
    from ..utils.shapes import bucket_channels
    c_pad = bucket_channels(c)
    # integer accumulands (quantized training): int8/int16 operands,
    # exact int32 accumulation on the MXU's low-precision path
    integer = jnp.issubdtype(vals.dtype, jnp.integer)
    op_dt = vals.dtype if integer else jnp.float32
    acc_dt = jnp.int32 if integer else jnp.float32

    # static FLOP/byte accounting from the TRACED shapes (obs/flops.py;
    # a Python side effect, so it fires once per fresh trace and costs
    # nothing at runtime — the comm.py trick applied to compute).  The
    # "hist" site carries the USEFUL channels only; the lane-pad MACs
    # go to the MFU-excluded "hist_pad" site (phase="pad")
    from ..obs.flops import (hist_flops_bytes, hist_pad_flops_bytes,
                             note_traced)
    note_traced("hist", *hist_flops_bytes(
        n, f, num_bins, channels=c,
        binned_itemsize=getattr(binned.dtype, "itemsize", 1),
        vals_itemsize=getattr(vals.dtype, "itemsize", 4),
        slotted=slot is not None and num_slots > 1),
        phase="grow")
    if c_pad > c:
        note_traced("hist_pad", *hist_pad_flops_bytes(n, f, num_bins,
                                                      channels=c),
                    phase="pad")

    # Pad the bin axis to a multiple of 64 so the [blk, F, Bp] -> [blk, F*Bp]
    # merge is a free relayout (the minor dim tiles onto the 128-lane
    # registers).  Measured on v5e: B=63 unpadded costs 14.3 ms/pass vs
    # 5.5 ms padded to 64; padding to 128 is SLOWER again (8.1 ms), and
    # even B=15 runs faster padded to 64 than to 16.  Padded bins compare
    # equal to nothing (bins < num_bins), so the extra columns stay zero
    # and are sliced off at the end.
    bp = max(64, -(-num_bins // 64) * 64)
    if block_rows <= 0:
        block_rows = hist_block_rows(f, bp,
                                     getattr(vals.dtype, "itemsize", 4),
                                     channels=c_pad)
    block_rows = min(block_rows, max(8, n))

    cv = vals.shape[1]                       # raw (unexpanded) channels
    pad = (-n) % block_rows
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        if slot is not None:
            slot = jnp.pad(slot, (0, pad), constant_values=-1)
    nblocks = (n + pad) // block_rows

    binned_b = binned.reshape(nblocks, block_rows, f)
    vals_b = vals.reshape(nblocks, block_rows, cv)
    iota = jnp.arange(bp, dtype=jnp.int32)
    xs = (binned_b, vals_b)
    if slot is not None:
        xs = xs + (slot.reshape(nblocks, block_rows),)
        kiota = jnp.arange(num_slots, dtype=jnp.int32)

    def body(acc, chunk):
        bins_blk, vals_blk = chunk[0], chunk[1]
        if slot is not None:
            # expand vals ⊗ onehot(slot) per block, fused into the scan:
            # the [N, cv*K] operand never exists in HBM.  The 0/1 slot
            # one-hot multiplies at the vals dtype (an int8 product of
            # an int8 value and {0, 1} cannot overflow)
            oh_s = (chunk[2][:, None] == kiota).astype(op_dt)
            vals_blk = (vals_blk[:, :, None] * oh_s[:, None, :]) \
                .reshape(block_rows, c)
        if c_pad > c:
            # lane-pad the accumuland operand: the extra columns are
            # exact zeros (no slot reaches them), sliced off after the
            # scan, so they cost MXU cycles, never numerics
            vals_blk = jnp.pad(vals_blk, ((0, 0), (0, c_pad - c)))
        onehot = (bins_blk.astype(jnp.int32)[:, :, None] == iota) \
            .astype(op_dt).reshape(block_rows, f * bp)
        # [C, block] x [block, F*Bp] -> [C, F*Bp]: the narrow C=3 axis maps
        # to output SUBLANES (padded 3->8) instead of lanes (3->128), a
        # measured ~2.2x win over the transposed orientation
        h = lax.dot_general(
            vals_blk, onehot,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=acc_dt)
        return acc + h, None

    acc0 = jnp.zeros((c_pad, f * bp), dtype=acc_dt)
    acc, _ = lax.scan(body, acc0, xs)
    return acc[:c].reshape(c, f, bp).transpose(1, 2, 0)[:, :num_bins, :]


def masked_histogram(binned: jax.Array, vals: jax.Array, leaf_of_row: jax.Array,
                     leaf: jax.Array, *, num_bins: int, block_rows: int = 0) -> jax.Array:
    """Histogram over only the rows whose current leaf == ``leaf``.

    The masked-full-pass equivalent of the reference's gathered smaller-leaf
    construction (cuda_histogram_constructor.cu) — static shapes, mask folded
    into the accumulands.
    """
    mask = (leaf_of_row == leaf).astype(vals.dtype)[:, None]
    return compute_histogram(binned, vals * mask, num_bins=num_bins,
                             block_rows=block_rows)


def feature_totals_residual(hist: jax.Array, vals: jax.Array) -> jax.Array:
    """Max absolute residual of the histogram's defining invariant:
    summing a feature's bins must reproduce the column totals of the
    accumulands, ``sum_b hist[f, b, c] == sum_n vals[n, c]`` for every
    feature ``f`` — the one-hot rows partition the rows exactly once.

    A scalar 0 (int accumulands) or ~rounding-sized value (f32) on a
    healthy device; a bit flip anywhere in the contraction shows up as
    a residual the size of the flipped magnitude.  Used by the
    integrity layer (lightgbm_tpu/integrity.py) as an attribution probe
    when a sticky histogram mismatch is being blackbox-dumped, and by
    the unit tests as a direct oracle on :func:`compute_histogram`.
    """
    tot = jnp.sum(hist, axis=1)                     # [F, C]
    col = jnp.sum(vals.astype(hist.dtype), axis=0)  # [C]
    return jnp.max(jnp.abs(tot - col[None, :]))
