"""Multi-process distributed e2e (VERDICT r2 task 9a): 2 REAL processes
over localhost exercise ``launch.init``'s actual jax.distributed path,
distributed binning, and data-parallel tree growth with genuine
cross-process gloo collectives — then the grown tree must equal a
single-process run (the contract the reference tests with socket
subprocesses, tests/distributed/_test_distributed.py:79-100)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "mp_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_data_parallel_matches_single(tmp_path):
    out = tmp_path / "mp_tree.json"
    _run_pod(WORKER, 2, out)
    mp = json.loads(out.read_text())

    # single-process reference: same data, same binning config
    from lightgbm_tpu.binning import BinMapper
    from lightgbm_tpu.grower import make_grower
    from lightgbm_tpu.ops.split import SplitParams
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    n, f = 4096, 10
    x = rng.randn(n, f).astype(np.float64)
    y = (x[:, 0] - 0.7 * x[:, 1] > 0).astype(np.float32)

    # bin with EXACTLY the workers' distributed-fitted mappers (dumped in
    # the record): distributed FindBin samples per process by design, so a
    # full-data refit here would legitimately differ
    from lightgbm_tpu.binning import BinType, MissingType
    mappers = []
    for spec in mp["mappers"]:
        m = BinMapper()
        m.bin_upper_bound = np.asarray(spec["bounds"], np.float64)
        m.num_bin = spec["num_bin"]
        m.bin_type = BinType.NUMERICAL
        m.missing_type = MissingType.NONE   # na_bin derives from this
        assert m.na_bin == spec["na_bin"]
        mappers.append(m)
    binned = np.column_stack(
        [mappers[j].value_to_bin(x[:, j]) for j in range(f)]
    ).astype(np.uint8)
    g = (0.5 - y).astype(np.float32)
    h = np.full(n, 0.25, np.float32)
    vals = jnp.asarray(np.stack([g, h, np.ones_like(g)], axis=1))

    B = max(m.num_bin for m in mappers)
    grow = make_grower(num_leaves=15, num_bins=B,
                       params=SplitParams(min_data_in_leaf=5))
    arrays = grow(jnp.asarray(binned), vals, jnp.ones(f, bool),
                  jnp.asarray([m.num_bin for m in mappers], jnp.int32),
                  jnp.asarray([m.na_bin for m in mappers], jnp.int32))

    assert mp["num_leaves"] == int(arrays.num_leaves)
    np.testing.assert_array_equal(mp["split_feature"],
                                  np.asarray(arrays.split_feature))
    np.testing.assert_array_equal(mp["threshold_bin"],
                                  np.asarray(arrays.threshold_bin))
    np.testing.assert_allclose(mp["leaf_value"],
                               np.asarray(arrays.leaf_value),
                               rtol=2e-4, atol=2e-5)


GOSS_WORKER = os.path.join(HERE, "mp_goss_worker.py")
LEARNER_WORKER = os.path.join(HERE, "mp_learner_worker.py")


def _run_pod(worker, nproc, out, extra_args=(), timeout=420):
    """Spawn an nproc-process localhost gloo pod and assert clean exit."""
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [subprocess.Popen(
        [sys.executable, worker, str(rank), str(nproc), str(port),
         str(out), *extra_args],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for rank in range(nproc)]
    outs = []
    for p in procs:
        try:
            o, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(o)
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"
    assert out.exists(), outs[0][-2000:]


def _single_controller_trees(learner):
    """The same training run on ONE controller with a 2-device mesh —
    the topology-invariance reference point."""
    sys.path.insert(0, HERE)
    from mp_learner_shared import PARAMS, ROUNDS, VARIANTS, global_data, \
        full_data_mappers
    from tests_goss_shared import tree_records
    from lightgbm_tpu import Dataset, train

    base, _, variant = learner.partition("+")
    x, y = global_data()
    params = dict(PARAMS, num_machines=2, tree_learner=base,
                  **VARIANTS[variant])
    ds = Dataset(x, label=y, bin_mappers=full_data_mappers(x),
                 params=params)
    bst = train(params, ds, num_boost_round=ROUNDS)
    return tree_records(bst), bst.predict(x[:256]), ROUNDS


def _check_learner_topology(tmp_path, learner):
    """2 processes x 1 device == 1 process x 2 devices, tree for tree
    (the reference's distributed contract for this learner,
    tree_learner.cpp:16-64 x _test_distributed.py:79-100)."""
    out = tmp_path / f"{learner}_trees.json"
    _run_pod(LEARNER_WORKER, 2, out, extra_args=(learner,))
    rec = json.loads(out.read_text())
    single, pred, rounds = _single_controller_trees(learner)

    mp_trees = rec["trees"]
    assert len(mp_trees) == len(single) == rounds
    for i, (mt, st) in enumerate(zip(mp_trees, single)):
        assert mt["split_feature"] == st["split_feature"], f"tree {i}"
        assert mt["threshold_bin"] == st["threshold_bin"], f"tree {i}"
        np.testing.assert_allclose(mt["leaf_value"], st["leaf_value"],
                                   rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(rec["pred_head"]), pred,
                               rtol=5e-4, atol=5e-4)


def test_two_process_feature_parallel_matches_single_controller(tmp_path):
    """tree_learner=feature on a REAL 2-process pod (VERDICT r4 task 6):
    data replicated per process, split search sharded over features."""
    _check_learner_topology(tmp_path, "feature")


def test_two_process_voting_parallel_matches_single_controller(tmp_path):
    """tree_learner=voting on a REAL 2-process pod (VERDICT r4 task 6):
    rows sharded, vote-compressed histogram reduction."""
    _check_learner_topology(tmp_path, "voting")


def test_two_process_feature_parallel_goss(tmp_path):
    """GOSS under multi-process feature-parallel: rows are replicated,
    so every rank must draw the SAME sample (no per-rank RNG fold-in) or
    the pod's split statistics silently diverge."""
    _check_learner_topology(tmp_path, "feature+goss")


def test_two_process_feature_parallel_bagging(tmp_path):
    """Bagging under multi-process feature-parallel: same replicated-rows
    contract as GOSS, through the _bagging_mask path."""
    _check_learner_topology(tmp_path, "feature+bag")


def test_two_process_goss_matches_single(tmp_path):
    """Global GOSS semantics (VERDICT r4 task 5): with binning held
    topology-invariant, 2-process data-parallel GOSS training must produce
    the SAME trees as one process over the concatenated rows — i.e. the
    top-rate threshold and the other-rate Bernoulli draws are global
    (goss.hpp:20-188 samples over the full data)."""
    out = tmp_path / "goss_trees.json"
    _run_pod(GOSS_WORKER, 2, out)
    rec = json.loads(out.read_text())

    sys.path.insert(0, HERE)
    from tests_goss_shared import GOSS_PARAMS, ROUNDS, global_data, \
        full_data_mappers, tree_records, synthetic_grads
    from lightgbm_tpu import Dataset, train
    import jax.numpy as jnp

    x, y = global_data()
    ds = Dataset(x, label=y, bin_mappers=full_data_mappers(x),
                 params=GOSS_PARAMS)
    bst = train(GOSS_PARAMS, ds, num_boost_round=ROUNDS)
    single = tree_records(bst)

    # 1) the sampling semantics, EXACT: rank 0's GOSS weight vector is
    # bitwise the first-half slice of the single-process weight vector
    # (global threshold + global-index-keyed Bernoulli draws)
    m = bst._model
    g_full, h_full = synthetic_grads(len(y))
    w0 = np.asarray(m._goss_vals(jnp.asarray(g_full),
                                 jnp.asarray(h_full), it=0))
    w0_rank0 = np.asarray(rec["w0_rank0"], np.float32)
    np.testing.assert_array_equal(w0_rank0, w0[:len(w0_rank0)])
    # the sample kept both strata
    assert (w0 == 1.0).any() and (w0 > 1.0).any()

    # 2) the trained models agree to float-accumulation noise (the 2-shard
    # psum reorders histogram sums, which can flip near-tie splits — same
    # tolerance class as the reference's distributed tests)
    mp_trees = rec["trees"]
    assert len(mp_trees) == len(single) == ROUNDS
    agree = sum(mt["split_feature"] == st["split_feature"]
                for mt, st in zip(mp_trees, single))
    assert agree >= ROUNDS - 2, f"only {agree}/{ROUNDS} trees structurally equal"
    pred = bst.predict(x[:256])
    np.testing.assert_allclose(np.asarray(rec["pred_head"]), pred,
                               rtol=5e-3, atol=5e-3)
