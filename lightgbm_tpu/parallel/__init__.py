from .mesh import make_mesh, default_mesh
from .data_parallel import make_dp_grower, shard_rows, pad_to_multiple
from .feature_parallel import make_fp_grower
