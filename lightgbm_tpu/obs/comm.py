"""Static bytes-on-the-wire accounting for collective call sites.

"GPU-acceleration for Large-scale Tree Boosting" (arXiv:1706.08359)
validates its scaling claims by instrumenting bytes moved per
iteration; the reference's distributed learners get the same number
implicitly from their hand-rolled ReduceScatter buffers.  Here the
collectives are XLA ops inside jitted shard_map programs, so runtime
counting would need host syncs — instead the byte math is derived
STATICALLY from the traced shapes: a ``CommLedger`` wraps each
``lax.psum`` / ``psum_scatter`` / ``all_gather`` call site, records
(site, collective, payload bytes, wire-byte estimate, cadence) once at
trace time, and returns the *identical* lax op.  Zero runtime cost,
zero extra syncs; registration re-runs idempotently on retrace.

Wire-byte model (ring algorithms, the standard cost model XLA's ICI
collectives follow to within the protocol constant):

- ``psum`` (all-reduce):        ``2 * (n-1)/n * payload`` per chip
- ``psum_scatter``:             ``(n-1)/n * input payload`` per chip
- ``all_gather``:               ``(n-1)/n * output payload`` per chip

Cadence tells the host-side accounting how often a site executes:
``"step"`` sites run once per grower super-step (histogram reduce,
best-split sync), ``"tree"`` sites once per tree (root totals) — the
driver multiplies by the fetched ``n_steps`` it already holds, so the
per-iteration counters cost nothing beyond arithmetic.
"""

from __future__ import annotations

import math
from typing import Any, Dict, NamedTuple, Tuple

from jax import lax


class CommSite(NamedTuple):
    site: str             # stable call-site name, e.g. "dp.hist_reduce"
    collective: str       # psum | psum_scatter | all_gather
    payload_bytes: int    # tensor bytes entering the collective
    wire_bytes: int       # estimated bytes crossing the interconnect/chip
    axis_size: int
    cadence: str          # "step" | "tree"


def _nbytes(x: Any) -> int:
    """Tensor bytes from a traced value or pytree of traced values."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(x):
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", None)
        itemsize = getattr(dtype, "itemsize", 4) if dtype is not None else 4
        total += int(math.prod(shape)) * itemsize
    return total


def wire_bytes(collective: str, payload: int, n: int) -> int:
    """Per-chip wire bytes under the ring model (module docstring).
    ``pmax`` follows the all-reduce cost (same ring, max combiner)."""
    if n <= 1:
        return 0
    frac = (n - 1) / n
    if collective in ("psum", "pmax"):
        return int(2 * frac * payload)
    # psum_scatter: payload = input bytes; all_gather: payload = OUTPUT
    # bytes (n * input) — callers pass the right one
    return int(frac * payload)


class CommLedger:
    """Per-grower collective ledger.  Builders create one, route their
    collectives through it, and attach it to the grower callable as
    ``comm`` so the driver can read the static site table."""

    def __init__(self, axis_size: int):
        self.axis_size = int(axis_size)
        self._sites: Dict[str, CommSite] = {}

    def _record(self, site: str, collective: str, payload: int,
                cadence: str, wire_payload: int = None) -> None:
        self._sites[site] = CommSite(
            site=site, collective=collective, payload_bytes=payload,
            wire_bytes=wire_bytes(collective,
                                  payload if wire_payload is None
                                  else wire_payload, self.axis_size),
            axis_size=self.axis_size, cadence=cadence)

    # -- wrapped collectives (identical semantics to the lax ops) -------
    def psum(self, x, axis_name: str, *, site: str,
             cadence: str = "step"):
        self._record(site, "psum", _nbytes(x), cadence)
        return lax.psum(x, axis_name)

    def pmax(self, x, axis_name: str, *, site: str,
             cadence: str = "step"):
        self._record(site, "pmax", _nbytes(x), cadence)
        return lax.pmax(x, axis_name)

    def psum_scatter(self, x, axis_name: str, *, site: str,
                     cadence: str = "step", **kw):
        self._record(site, "psum_scatter", _nbytes(x), cadence)
        return lax.psum_scatter(x, axis_name, **kw)

    def all_gather(self, x, axis_name: str, *, site: str,
                   cadence: str = "step", **kw):
        payload = _nbytes(x)
        # wire model wants OUTPUT bytes for all_gather
        self._record(site, "all_gather", payload, cadence,
                     wire_payload=payload * self.axis_size)
        return lax.all_gather(x, axis_name, **kw)

    def note_all_gather(self, x, *, site: str,
                        cadence: str = "step") -> None:
        """Record an all_gather performed elsewhere (ops/split.py
        ``gather_best`` stays collective-owning; the learner builders
        note its payload here at trace time)."""
        payload = _nbytes(x)
        self._record(site, "all_gather", payload, cadence,
                     wire_payload=payload * self.axis_size)

    # -- reading --------------------------------------------------------
    def sites(self) -> Tuple[CommSite, ...]:
        return tuple(self._sites[k] for k in sorted(self._sites))

    def bytes_per_iteration(self, n_steps: int) -> int:
        """Estimated wire bytes for one boosting iteration that ran
        ``n_steps`` grower loop steps."""
        return sum(s.wire_bytes * (n_steps if s.cadence == "step" else 1)
                   for s in self.sites())


def dp_hist_bytes_per_iter(n_shards: int, chunk: int, padded_bins: int,
                           n_steps: int, split_batch: int = 1,
                           itemsize: int = 4) -> int:
    """Closed-form wire-byte estimate for the data-parallel owner-shard
    histogram reduce-scatter over one iteration — the PR 1 per-shard
    hist-bytes math (``OwnerShardPlan.hist_bytes``) times the reduce
    cadence, usable without building a mesh (bench.py extras).  The
    scattered tensor per step is ``[n_shards * chunk * split_batch,
    padded_bins, 3]`` at ``itemsize``-byte lanes: f32 for the default
    path, int32 for quantized training (quant_train) — 4 bytes either
    way, HALF the reference's f64 ``ReduceScatter`` wire format (its
    hist_t is double; see docs/Quantized-Training.md for why a 16-bit
    wire format is unsafe: local per-bin sums need 8 + log2(rows)
    bits, so int16 lanes would wrap on any real shard)."""
    payload = (n_shards * chunk * split_batch * padded_bins * 3
               * int(itemsize))
    return wire_bytes("psum_scatter", payload, n_shards) * n_steps
