"""Quantized low-precision training (ISSUE 13, docs/Quantized-Training.md).

The acceptance bars, as tests:

- **metric-parity harness** — quant vs f32 training on all four
  objective families (regression / binary / multiclass / lambdarank)
  stays within a pinned epsilon; this gate is the feature's contract;
- **default off is byte-identical** — ``quant_train=false`` trains the
  exact pre-quantization trees (only the echoed parameter line moves);
- **dp==serial int32 histogram identity** — the quantized histogram is
  an exact integer accumulation, so the sharded reduce is BITWISE equal
  to the serial pass (stronger than the f32 path's per-program
  determinism), and the trained tree structure matches serial;
- **kill+resume byte identity** — the stochastic-rounding stream is
  iteration-keyed, so crash+resume replays a straight run exactly;
- **fused == per-iteration** — the chunked ``lax.scan`` path quantizes
  with the same in-graph scales and keys;
- **ledger-proven HBM cut** — the static ledger (obs/flops.py) shows
  >= 2x lower histogram HBM bytes for int8 at a narrow shape, rising
  intensity, and the quantize/dequant sites; ``perf.hist.*`` keys carry
  the moved bound;
- **comm re-accounting** — the owner-shard reduce-scatter payload is
  recorded at its true int32 width, plus the quant-scale pmax site.
"""

import glob
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.quantize import (QuantSpec, counter_uniform,
                                       quant_scales, quantize_stack)

_rs = np.random.RandomState(11)
X = _rs.randn(600, 6)
YREG = (2.0 * X[:, 0] - X[:, 1] + 0.1 * _rs.randn(600)).astype(np.float32)
YBIN = (X[:, 0] - X[:, 1] + 0.2 * _rs.randn(600) > 0).astype(np.float32)

BASE = {"objective": "binary", "num_leaves": 15, "max_bin": 31,
        "min_data_in_leaf": 5, "verbosity": -1, "tpu_learner": "masked",
        "fused_chunk": 0}


def _train(p, x=X, y=YBIN, rounds=3, **dskw):
    ds = lgb.Dataset(x, label=y, params=dict(p), **dskw)
    return lgb.train(dict(p), ds, num_boost_round=rounds)


def _strip_params(model_text: str) -> str:
    """Tree sections only: the parameters echo legitimately differs
    when a param is passed explicitly."""
    return model_text.split("parameters:")[0]


def _auc(y, s):
    r = np.argsort(np.argsort(s)) + 1
    npos = int((y > 0).sum())
    nneg = len(y) - npos
    return float((r[y > 0].sum() - npos * (npos + 1) / 2)
                 / max(npos * nneg, 1))


def _ndcg_at(y, s, groups, k=5):
    out, off = [], 0
    for g in groups:
        yy, ss = y[off:off + g], s[off:off + g]
        off += g
        order = np.argsort(-ss)[:k]
        dcg = ((2.0 ** yy[order] - 1)
               / np.log2(np.arange(len(order)) + 2)).sum()
        ideal = np.sort(yy)[::-1][:k]
        idcg = ((2.0 ** ideal - 1)
                / np.log2(np.arange(len(ideal)) + 2)).sum()
        out.append(dcg / idcg if idcg > 0 else 1.0)
    return float(np.mean(out))


# ---------------------------------------------------------------------------
# quantizer units (ops/quantize.py)
# ---------------------------------------------------------------------------

class TestQuantizer:
    def test_zero_rows_stay_zero(self):
        """Out-of-bag / padded rows carry exact zeros; stochastic
        rounding must never push them off zero."""
        import jax.numpy as jnp
        spec = QuantSpec(bits=8, stochastic=True, seed=3)
        vals = jnp.zeros((64, 3), jnp.float32)
        scales = jnp.full(3, 0.01, jnp.float32)
        q = quantize_stack(vals, scales, spec, 5, 0)
        assert q.dtype == jnp.int8
        assert not np.asarray(q).any()

    def test_stochastic_rounding_is_unbiased(self):
        import jax.numpy as jnp
        spec = QuantSpec(bits=8, stochastic=True, seed=0)
        v = jnp.full((4000, 3), 0.3, jnp.float32)
        scales = jnp.ones(3, jnp.float32)
        q = np.asarray(quantize_stack(v, scales, spec, 1, 0), np.float64)
        assert set(np.unique(q)) <= {0.0, 1.0}
        assert abs(q.mean() - 0.3) < 0.02

    def test_nearest_mode_deterministic(self):
        import jax.numpy as jnp
        spec = QuantSpec(bits=16, stochastic=False, seed=0)
        v = jnp.asarray(_rs.randn(100, 3).astype(np.float32))
        s = quant_scales(v, spec.qmax)
        q1 = np.asarray(quantize_stack(v, s, spec, 1, 0))
        q2 = np.asarray(quantize_stack(v, s, spec, 99, 0))
        np.testing.assert_array_equal(q1, q2)   # iteration key unused
        assert q1.dtype == np.int16

    def test_rounding_stream_slices_by_global_row(self):
        """The dp==serial identity's core: rows quantized on a shard
        with a global offset draw the SAME uniforms as the serial pass
        draws for those rows."""
        import jax.numpy as jnp
        full = np.asarray(counter_uniform(
            jnp.arange(300, dtype=jnp.int32), 3, 7, 42))
        part = np.asarray(counter_uniform(
            100 + jnp.arange(50, dtype=jnp.int32), 3, 7, 42))
        np.testing.assert_array_equal(full[100:150], part)
        assert (full >= 0).all() and (full < 1).all()

    def test_scale_covers_range(self):
        import jax.numpy as jnp
        spec = QuantSpec(bits=8)
        v = jnp.asarray(_rs.randn(500, 3).astype(np.float32)) * 37.0
        s = quant_scales(v, spec.qmax)
        q = np.asarray(quantize_stack(v, s, spec, 0, 0), np.int32)
        assert q.min() >= -127 and q.max() <= 127
        # dequantized extremum reproduces the true extremum to one step
        err = np.abs(q * np.asarray(s)[None, :] - np.asarray(v))
        assert (err <= np.asarray(s)[None, :] + 1e-7).all()


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

class TestQuantConfig:
    def test_bad_bits_rejected(self):
        with pytest.raises(ValueError, match="quant_bits"):
            lgb.train(dict(BASE, quant_train=True, quant_bits=12),
                      lgb.Dataset(X, label=YBIN), num_boost_round=1)

    def test_bad_round_rejected(self):
        with pytest.raises(ValueError, match="quant_round"):
            lgb.train(dict(BASE, quant_train=True, quant_round="up"),
                      lgb.Dataset(X, label=YBIN), num_boost_round=1)

    def test_default_off_is_byte_identical(self):
        a = _train(BASE)
        b = _train(dict(BASE, quant_train=False))
        assert _strip_params(a.model_to_string()) \
            == _strip_params(b.model_to_string())


# ---------------------------------------------------------------------------
# the metric-parity harness: the feature's acceptance gate
# ---------------------------------------------------------------------------

# (params, metric fn on (model, x, y, groups), pinned epsilon).
# Epsilons are deliberately tight for trees this small: int8 stochastic
# rounding perturbs leaf values by ~1/127 of the grad scale, which these
# shallow ensembles absorb almost entirely.
_FAMILIES = {
    "regression": (dict(objective="regression"), "l2", 0.12),
    "binary": (dict(objective="binary"), "auc", 0.02),
    "multiclass": (dict(objective="multiclass", num_class=3), "mlogloss",
                   0.10),
    "lambdarank": (dict(objective="lambdarank"), "ndcg", 0.05),
}


def _family_data(family):
    if family == "multiclass":
        y = (np.digitize(X[:, 0] + 0.3 * X[:, 1], [-0.5, 0.5])
             ).astype(np.float32)
        return X, y, None
    if family == "lambdarank":
        groups = [20] * 30
        y = np.clip(np.round(X[:, 0] - X[:, 1]
                             + 0.3 * _rs.randn(600)), 0, 3).astype(
            np.float32)
        return X, y, groups
    if family == "binary":
        return X, YBIN, None
    return X, YREG, None


def _family_metric(kind, model, x, y, groups):
    pred = model.predict(x)
    if kind == "l2":
        return float(np.mean((pred - y) ** 2))
    if kind == "auc":
        return _auc(y, pred)
    if kind == "mlogloss":
        p = np.clip(pred[np.arange(len(y)), y.astype(int)], 1e-9, 1.0)
        return float(-np.mean(np.log(p)))
    return _ndcg_at(y, pred, groups)


class TestMetricParityHarness:
    @pytest.mark.parametrize("family", sorted(_FAMILIES))
    @pytest.mark.parametrize("bits", [8, 16])
    def test_quant_within_epsilon_of_f32(self, family, bits):
        over, kind, eps = _FAMILIES[family]
        x, y, groups = _family_data(family)
        dskw = {"group": groups} if groups else {}
        p = dict(BASE, **over)
        m_f32 = _train(p, x, y, rounds=5, **dskw)
        m_q = _train(dict(p, quant_train=True, quant_bits=bits),
                     x, y, rounds=5, **dskw)
        v_f32 = _family_metric(kind, m_f32, x, y, groups)
        v_q = _family_metric(kind, m_q, x, y, groups)
        if kind == "l2":
            # scale-dependent: compare relatively
            assert abs(v_q - v_f32) <= eps * max(v_f32, 1e-9), \
                (family, bits, v_f32, v_q)
        else:
            assert abs(v_q - v_f32) <= eps, (family, bits, v_f32, v_q)


# ---------------------------------------------------------------------------
# exactness properties
# ---------------------------------------------------------------------------

class TestInt32HistogramIdentity:
    def test_dp_reduce_bitwise_equals_serial(self):
        """The int32 accumulation is exact and order-independent, so
        the sharded psum of per-shard quantized histograms equals the
        serial pass BITWISE — the dp==serial histogram identity."""
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P
        from lightgbm_tpu.ops.histogram import compute_histogram
        from lightgbm_tpu.parallel import make_mesh
        from lightgbm_tpu.utils.jax_compat import shard_map

        n, f, b = 512, 5, 16
        binned = _rs.randint(0, b, size=(n, f)).astype(np.uint8)
        vals = _rs.randn(n, 3).astype(np.float32)
        spec = QuantSpec(bits=8, stochastic=True, seed=9)
        scales = quant_scales(jnp.asarray(vals), spec.qmax)
        q = quantize_stack(jnp.asarray(vals), scales, spec, 4, 0)
        serial = np.asarray(compute_histogram(
            jnp.asarray(binned), q, num_bins=b))
        assert serial.dtype == np.int32

        mesh = make_mesh((8,), ("data",), jax.devices()[:8])

        def shard_fn(bb, vv):
            # per-shard rows quantized with the GLOBAL row offset:
            # identical ints to the serial pass, then an exact psum
            off = lax.axis_index("data") * (n // 8)
            qq = quantize_stack(vv, scales, spec, 4, off)
            return lax.psum(compute_histogram(bb, qq, num_bins=b),
                            "data")

        fn = jax.jit(shard_map(
            shard_fn, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=P(), check_vma=False))
        sharded = np.asarray(fn(binned, vals))
        np.testing.assert_array_equal(serial, sharded)

    def test_dp_trains_serial_structure(self):
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        p = dict(BASE, quant_train=True)
        ser = _train(p)
        dp = _train(dict(p, tree_learner="data"))
        for a, b in zip(ser.dump_model()["tree_info"],
                        dp.dump_model()["tree_info"]):
            sa, sb = a["tree_structure"], b["tree_structure"]
            assert sa.get("split_feature") == sb.get("split_feature")
            assert sa.get("threshold") == sb.get("threshold")
        np.testing.assert_allclose(ser.predict(X), dp.predict(X),
                                   rtol=1e-5, atol=1e-6)

    def test_fused_chunk_matches_per_iteration(self):
        """The fused lax.scan path quantizes with the same in-graph
        scales and iteration keys — byte-identical trees."""
        p = dict(BASE, objective="regression", quant_train=True)
        a = _train(p, y=YREG, rounds=4)
        b = _train(dict(p, fused_chunk=2), y=YREG, rounds=4)
        assert _strip_params(a.model_to_string()) \
            == _strip_params(b.model_to_string())

    def test_partitioned_matches_masked_structure(self):
        p = dict(BASE, quant_train=True)
        m = _train(p)
        pt = _train(dict(p, tpu_learner="partitioned"))
        for a, b in zip(m.dump_model()["tree_info"],
                        pt.dump_model()["tree_info"]):
            assert a["tree_structure"].get("split_feature") \
                == b["tree_structure"].get("split_feature")

    def test_voting_and_feature_learners_train(self):
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        for tl in ("voting", "feature"):
            m = _train(dict(BASE, quant_train=True, tree_learner=tl),
                       rounds=2)
            assert m.num_trees() == 2
            assert _auc(YBIN, m.predict(X)) > 0.8
            if tl == "voting":
                # the scale pmax is recorded under the VOTING learner's
                # own label, not misattributed to dp
                sites = {s.site for s in m._model.grower.comm.sites()}
                assert "voting.quant_scale" in sites
                assert "dp.quant_scale" not in sites

    def test_int32_accumulator_overflow_refused(self):
        """rows * qmax must stay under 2^31 (a degenerate feature can
        put every row in ONE bin, wrapping the int32 histogram
        silently): quant_bits=16 is refused past ~65k rows with an
        actionable error; the same rows fit easily under quant_bits=8
        (bound ~16.9M)."""
        n = 66_000                       # > (2^31-1) // 32767 == 65538
        xb = _rs.randn(n, 2).astype(np.float32)
        yb = (xb[:, 0] > 0).astype(np.float32)
        p = dict(BASE, quant_train=True, quant_bits=16)
        with pytest.raises(ValueError, match="int32 histogram"):
            lgb.train(dict(p), lgb.Dataset(xb, label=yb, params=dict(p)),
                      num_boost_round=1)
        m = _train(dict(BASE, quant_train=True, quant_bits=8,
                        num_leaves=4), x=xb, y=yb, rounds=1)
        assert m.num_trees() == 1

    def test_sparse_storage_refused(self):
        sp = pytest.importorskip("scipy.sparse")
        dense = _rs.randn(400, 50)
        dense[_rs.rand(400, 50) > 0.04] = 0.0    # ~2 nnz/row, 50 cols
        xs = sp.csr_matrix(dense)
        y = (dense[:, 0] + 0.1 * _rs.randn(400) > 0).astype(np.float32)
        p = dict(BASE, quant_train=True, enable_sparse=True,
                 enable_bundle=False)
        with pytest.raises(ValueError, match="quant_train"):
            lgb.train(dict(p), lgb.Dataset(xs, label=y, params=dict(p)),
                      num_boost_round=1)


# ---------------------------------------------------------------------------
# crash+resume byte identity under quantized stochastic rounding
# ---------------------------------------------------------------------------

class TestQuantResume:
    def test_kill_and_resume_byte_identical(self, tmp_path):
        from lightgbm_tpu.utils import faultinject
        from lightgbm_tpu.utils.faultinject import InjectedKill
        out = str(tmp_path / "m.txt")
        p = dict(BASE, objective="regression", quant_train=True,
                 snapshot_freq=3, output_model=out)

        def ds():
            return lgb.Dataset(X, label=YREG, params=dict(p))

        straight = lgb.train(dict(p), ds(), num_boost_round=7)
        s_straight = straight.model_to_string()
        for f in glob.glob(out + "*"):
            os.unlink(f)
        faultinject.configure("snapshot_kill:4")
        try:
            with pytest.raises(InjectedKill):
                lgb.train(dict(p), ds(), num_boost_round=7)
        finally:
            faultinject.clear()
        resumed = lgb.train(dict(p, resume=True), ds(),
                            num_boost_round=7)
        # iteration-keyed rounding: the resumed run replays the exact
        # stochastic stream of the straight run
        assert _strip_params(resumed.model_to_string()) \
            == _strip_params(s_straight)


# ---------------------------------------------------------------------------
# the ledger-proven HBM cut + perf.* instrument + comm re-accounting
# ---------------------------------------------------------------------------

class TestLedgerAndPerfKeys:
    def test_hist_hbm_bytes_drop_2x_and_intensity_rises(self):
        """The acceptance criterion: >= 2x lower perf.hist.hbm_bytes
        for quant_bits=8 vs f32 at identical shapes, with intensity
        rising accordingly (narrow feature count: the vals stream is
        the dominant histogram read there)."""
        from lightgbm_tpu.obs.flops import FlopLedger
        n, f, b = 1_000_000, 4, 63
        led8 = FlopLedger.for_training(n, f, b, vals_itemsize=1,
                                       quant=True)
        led16 = FlopLedger.for_training(n, f, b, vals_itemsize=2,
                                        quant=True)
        led32 = FlopLedger.for_training(n, f, b)
        s8 = {s.site: s for s in led8.sites()}
        s16 = {s.site: s for s in led16.sites()}
        s32 = {s.site: s for s in led32.sites()}
        assert s32["hist"].hbm_bytes >= 2 * s8["hist"].hbm_bytes
        assert s32["hist"].hbm_bytes > s16["hist"].hbm_bytes
        # FLOPs unchanged -> intensity rises by the byte ratio
        assert s8["hist"].flops == s32["hist"].flops
        i8 = s8["hist"].flops / s8["hist"].hbm_bytes
        i32 = s32["hist"].flops / s32["hist"].hbm_bytes
        assert i8 >= 2 * i32
        # the new sites exist only under quant
        assert "quantize" in s8 and "dequant" in s8
        assert "quantize" not in s32 and "dequant" not in s32

    def test_perf_hist_keys_show_the_bound(self):
        """perf.hist.* in the telemetry snapshot (per-site roofline
        join, obs/attrib.py): quant halves-or-better the histogram
        bytes vs an f32 run of the SAME narrow shape."""
        # enough rows that the per-pass vals read dominates the [F,B,3]
        # histogram write in the byte formula (as it does at real scale)
        xn = _rs.randn(4000, 4)
        yn = (2.0 * xn[:, 0] - xn[:, 1]
              + 0.1 * _rs.randn(4000)).astype(np.float32)

        def snap_for(extra):
            # pinned peaks put the ridge point (150 FLOP/byte) between
            # the f32 (~92) and int8 (~198) histogram intensities, so
            # the roofline verdict itself must flip memory -> compute
            p = dict(BASE, objective="regression", telemetry=True,
                     telemetry_peak_flops=1.5e13,
                     telemetry_peak_hbm_gbs=100.0, **extra)
            m = _train(p, x=xn, y=yn, rounds=2)
            return m.telemetry_snapshot()

        s_f32 = snap_for({})
        s_q8 = snap_for({"quant_train": True})
        assert s_q8["perf.hist.hbm_bytes"] * 2 \
            <= s_f32["perf.hist.hbm_bytes"]
        assert s_q8["perf.hist.intensity_flops_per_byte"] \
            >= 2 * s_f32["perf.hist.intensity_flops_per_byte"]
        assert s_f32["perf.hist.bound"] == "memory"
        assert s_q8["perf.hist.bound"] == "compute"   # the bound moved
        assert "perf.quantize.flops" in s_q8
        assert "perf.dequant.flops" in s_q8

    def test_dp_comm_ledger_reaccounts_quant(self):
        """The owner-shard reduce-scatter payload is recorded at its
        true int32 width (4-byte lanes — half the reference's f64
        ReduceScatter format), and the quant-scale pmax site appears."""
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device CPU mesh")
        from lightgbm_tpu.obs.comm import dp_hist_bytes_per_iter
        m = _train(dict(BASE, quant_train=True, tree_learner="data"),
                   rounds=2)
        comm = m._model.grower.comm
        sites = {s.site: s for s in comm.sites()}
        assert "dp.quant_scale" in sites
        assert sites["dp.quant_scale"].collective == "pmax"
        assert sites["dp.quant_scale"].payload_bytes == 3 * 4
        plan = m._model.grower.plan
        hr = sites["dp.hist_reduce"]
        # [n_shards * chunk, B, 3] int32
        assert hr.payload_bytes == 8 * plan.chunk * 31 * 3 * 4
        assert hr.wire_bytes == dp_hist_bytes_per_iter(
            8, plan.chunk, 31, n_steps=1, itemsize=4) \
            // 1  # one step

    def test_block_rows_scale_with_vals_width(self):
        """Satellite: hist_block_rows sizes the row block by the actual
        vals dtype width — int8 packs get 4x the f32 block (until the
        global cap)."""
        from lightgbm_tpu.ops.histogram import (HIST_BLOCK_ROWS,
                                                hist_block_rows)
        f, bp = 968, 256
        b4 = hist_block_rows(f, bp, 4)
        b1 = hist_block_rows(f, bp, 1)
        assert b1 >= 2 * b4           # wide shape: budget-bound
        assert b1 == min(4 * b4, HIST_BLOCK_ROWS) or b1 >= 2 * b4
        # narrow shapes stay at the measured cap either way
        assert hist_block_rows(28, 64, 1) == HIST_BLOCK_ROWS
        assert hist_block_rows(28, 64, 4) == HIST_BLOCK_ROWS
