"""Full-surface C API closure (c_api.h entry points beyond the core
lifecycle): sampled-column / by-reference streaming construction, subset,
feature merge, dumps, model surgery (merge/shuffle/leaf get-set),
leaf-pred refit, reset-training-data, bounds, CSC/Mats/sparse-output
prediction, the CSR FastConfig path, sampling utilities and the log
callback — every remaining LGBM_* export in libcapi_train.so."""

import ctypes
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from test_capi_train import _ensure_built, SO

_BUILD_ERR = _ensure_built()
pytestmark = pytest.mark.skipif(bool(_BUILD_ERR), reason=_BUILD_ERR)

F64, I32, I64, F32 = 1, 2, 3, 0


@pytest.fixture(scope="module")
def lib():
    lib = ctypes.CDLL(SO)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _err(lib):
    return lib.LGBM_GetLastError()


def _data(n=500, f=6, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float32)
    return np.ascontiguousarray(x, np.float64), y


def _make_dataset(lib, x, y, params=b"max_bin=31 verbosity=-1"):
    n, f = x.shape
    ds = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromMat(
        x.ctypes.data_as(ctypes.c_void_p), F64, n, f, 1, params, None,
        ctypes.byref(ds))
    assert rc == 0, _err(lib)
    rc = lib.LGBM_DatasetSetField(ds, b"label",
                                  y.ctypes.data_as(ctypes.c_void_p),
                                  n, F32)
    assert rc == 0, _err(lib)
    return ds


def _make_booster(lib, ds, params=b"objective=binary num_leaves=7 "
                               b"verbosity=-1", iters=5):
    bst = ctypes.c_void_p()
    rc = lib.LGBM_BoosterCreate(ds, params, ctypes.byref(bst))
    assert rc == 0, _err(lib)
    fin = ctypes.c_int(0)
    for _ in range(iters):
        assert lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0
    return bst


def test_dump_param_aliases(lib):
    buf = ctypes.create_string_buffer(1 << 20)
    out_len = ctypes.c_int64(0)
    rc = lib.LGBM_DumpParamAliases(len(buf), ctypes.byref(out_len), buf)
    assert rc == 0, _err(lib)
    import json
    aliases = json.loads(buf.value.decode())
    assert "eta" in aliases["learning_rate"]
    assert out_len.value > 100


def test_sample_count_and_indices(lib):
    out = ctypes.c_int(0)
    assert lib.LGBM_GetSampleCount(
        1_000_000, b"bin_construct_sample_cnt=5000", ctypes.byref(out)) == 0
    assert out.value == 5000
    idx = np.zeros(1000, np.int32)
    out_len = ctypes.c_int32(0)
    assert lib.LGBM_SampleIndices(
        1000, b"bin_construct_sample_cnt=200",
        idx.ctypes.data_as(ctypes.c_void_p), ctypes.byref(out_len)) == 0
    got = idx[:out_len.value]
    assert out_len.value == 200
    assert len(np.unique(got)) == 200 and got.max() < 1000
    assert (np.diff(got) > 0).all()      # sorted, like the reference


def test_sampled_column_streaming_train(lib):
    x, y = _data(400, 4, seed=1)
    cols = [np.ascontiguousarray(x[:200, j]) for j in range(4)]
    col_ptrs = (ctypes.POINTER(ctypes.c_double) * 4)(
        *[c.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for c in cols])
    num_per_col = (ctypes.c_int * 4)(*[200] * 4)
    ds = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromSampledColumn(
        col_ptrs, None, 4, num_per_col, 200, 400,
        b"max_bin=31 verbosity=-1", ctypes.byref(ds))
    assert rc == 0, _err(lib)
    # push rows in two chunks, set label, train
    for lo, hi in ((0, 250), (250, 400)):
        chunk = np.ascontiguousarray(x[lo:hi])
        rc = lib.LGBM_DatasetPushRows(
            ds, chunk.ctypes.data_as(ctypes.c_void_p), F64, hi - lo, 4, lo)
        assert rc == 0, _err(lib)
    assert lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 400, F32) == 0
    bst = _make_booster(lib, ds)
    it = ctypes.c_int(0)
    assert lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)) == 0
    assert it.value == 5
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_create_by_reference_and_push_csr(lib):
    from scipy.sparse import csr_matrix
    x, y = _data(300, 5, seed=2)
    ref = _make_dataset(lib, x, y)
    nd = ctypes.c_int(0)
    assert lib.LGBM_DatasetGetNumData(ref, ctypes.byref(nd)) == 0

    ds = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateByReference(ref, ctypes.c_int64(300),
                                           ctypes.byref(ds))
    assert rc == 0, _err(lib)
    csr = csr_matrix(x)
    indptr = csr.indptr.astype(np.int32)
    rc = lib.LGBM_DatasetPushRowsByCSR(
        ds, indptr.ctypes.data_as(ctypes.c_void_p), I32,
        csr.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        csr.data.ctypes.data_as(ctypes.c_void_p), F64,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(csr.nnz),
        ctypes.c_int64(5), ctypes.c_int64(0))
    assert rc == 0, _err(lib)
    assert lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), 300, F32) == 0
    nf = ctypes.c_int(0)
    assert lib.LGBM_DatasetGetNumFeature(ds, ctypes.byref(nf)) == 0
    assert nf.value == 5
    # bin mappers aligned with the reference dataset
    nb_ref = ctypes.c_int(0)
    nb_new = ctypes.c_int(0)
    assert lib.LGBM_DatasetGetFeatureNumBin(ref, 0, ctypes.byref(nb_ref)) == 0
    assert lib.LGBM_DatasetGetFeatureNumBin(ds, 0, ctypes.byref(nb_new)) == 0
    assert nb_ref.value == nb_new.value > 2
    lib.LGBM_DatasetFree(ds)
    lib.LGBM_DatasetFree(ref)


def test_subset_and_dump_text(lib, tmp_path):
    x, y = _data(200, 4, seed=3)
    ds = _make_dataset(lib, x, y)
    idx = np.arange(0, 200, 2, dtype=np.int32)
    sub = ctypes.c_void_p()
    rc = lib.LGBM_DatasetGetSubset(
        ds, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(idx),
        b"", ctypes.byref(sub))
    assert rc == 0, _err(lib)
    nd = ctypes.c_int(0)
    assert lib.LGBM_DatasetGetNumData(sub, ctypes.byref(nd)) == 0
    assert nd.value == 100
    out = tmp_path / "dump.txt"
    assert lib.LGBM_DatasetDumpText(ds, str(out).encode()) == 0
    lines = out.read_text().splitlines()
    assert len(lines) == 201            # header + rows
    lib.LGBM_DatasetFree(sub)
    lib.LGBM_DatasetFree(ds)


def test_update_param_checking(lib):
    assert lib.LGBM_DatasetUpdateParamChecking(
        b"max_bin=31 verbosity=-1", b"max_bin=31 verbosity=1") == 0
    assert lib.LGBM_DatasetUpdateParamChecking(
        b"max_bin=31", b"max_bin=63") == -1
    assert b"max_bin" in _err(lib)


def test_add_features_from(lib):
    x, y = _data(150, 3, seed=4)
    x2 = np.ascontiguousarray(np.random.RandomState(5).randn(150, 2))
    a = _make_dataset(lib, x, y)
    b = _make_dataset(lib, x2, y)
    assert lib.LGBM_DatasetAddFeaturesFrom(a, b) == 0, _err(lib)
    nf = ctypes.c_int(0)
    assert lib.LGBM_DatasetGetNumFeature(a, ctypes.byref(nf)) == 0
    assert nf.value == 5
    lib.LGBM_DatasetFree(a)
    lib.LGBM_DatasetFree(b)


def test_feature_names_list_variant(lib):
    x, y = _data(150, 3, seed=6)
    ds = _make_dataset(lib, x, y)
    names = (ctypes.c_char_p * 3)(b"aa", b"bb", b"cc")
    assert lib.LGBM_DatasetSetFeatureNames(ds, names, 3) == 0, _err(lib)
    bufs = [ctypes.create_string_buffer(64) for _ in range(3)]
    arr = (ctypes.c_char_p * 3)(*[ctypes.addressof(b) for b in bufs])
    out_n = ctypes.c_int(0)
    out_need = ctypes.c_size_t(0)
    rc = lib.LGBM_DatasetGetFeatureNames(
        ds, 3, ctypes.byref(out_n), ctypes.c_size_t(64),
        ctypes.byref(out_need), arr)
    assert rc == 0, _err(lib)
    assert out_n.value == 3
    assert [b.value for b in bufs] == [b"aa", b"bb", b"cc"]
    lib.LGBM_DatasetFree(ds)


def test_model_surgery_and_bounds(lib):
    x, y = _data(seed=7)
    ds = _make_dataset(lib, x, y)
    bst = _make_booster(lib, ds, iters=4)

    k = ctypes.c_int(0)
    assert lib.LGBM_BoosterNumModelPerIteration(bst, ctypes.byref(k)) == 0
    assert k.value == 1
    total = ctypes.c_int(0)
    assert lib.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(total)) == 0
    assert total.value == 4
    lin = ctypes.c_int(9)
    assert lib.LGBM_BoosterGetLinear(bst, ctypes.byref(lin)) == 0
    assert lin.value == 0

    lv = ctypes.c_double(0.0)
    assert lib.LGBM_BoosterGetLeafValue(bst, 0, 0, ctypes.byref(lv)) == 0
    assert lib.LGBM_BoosterSetLeafValue(
        bst, 0, 0, ctypes.c_double(lv.value + 0.25)) == 0
    lv2 = ctypes.c_double(0.0)
    assert lib.LGBM_BoosterGetLeafValue(bst, 0, 0, ctypes.byref(lv2)) == 0
    assert abs(lv2.value - lv.value - 0.25) < 1e-12

    hi = ctypes.c_double(0.0)
    lo = ctypes.c_double(0.0)
    assert lib.LGBM_BoosterGetUpperBoundValue(bst, ctypes.byref(hi)) == 0
    assert lib.LGBM_BoosterGetLowerBoundValue(bst, ctypes.byref(lo)) == 0
    assert hi.value > lo.value

    # shuffle: model count unchanged, tree multiset preserved
    assert lib.LGBM_BoosterShuffleModels(bst, 0, -1) == 0, _err(lib)
    assert lib.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(total)) == 0
    assert total.value == 4

    # merge another booster in
    bst2 = _make_booster(lib, ds, iters=2)
    assert lib.LGBM_BoosterMerge(bst, bst2) == 0, _err(lib)
    assert lib.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(total)) == 0
    assert total.value == 6
    lib.LGBM_BoosterFree(bst2)
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_get_predict_and_calc_num(lib):
    x, y = _data(seed=8)
    n = len(y)
    ds = _make_dataset(lib, x, y)
    bst = _make_booster(lib, ds, iters=3)
    cnt = ctypes.c_int64(0)
    assert lib.LGBM_BoosterGetNumPredict(bst, 0, ctypes.byref(cnt)) == 0
    assert cnt.value == n
    out = np.zeros(n, np.float64)
    out_len = ctypes.c_int64(0)
    rc = lib.LGBM_BoosterGetPredict(
        bst, 0, ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, _err(lib)
    assert out_len.value == n
    assert ((out > 0) & (out < 1)).all()   # sigmoid-transformed
    # a reasonable classifier on train data
    assert ((out > 0.5) == (y > 0)).mean() > 0.8

    want = ctypes.c_int64(0)
    assert lib.LGBM_BoosterCalcNumPredict(bst, 10, 3, 0, -1,
                                          ctypes.byref(want)) == 0
    assert want.value == 10 * (x.shape[1] + 1)
    assert lib.LGBM_BoosterCalcNumPredict(bst, 10, 2, 0, -1,
                                          ctypes.byref(want)) == 0
    assert want.value == 10 * 3
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_reset_training_data_and_refit(lib):
    x, y = _data(seed=9)
    ds = _make_dataset(lib, x, y)
    bst = _make_booster(lib, ds, iters=3)

    x2, y2 = _data(seed=10)
    ds2 = _make_dataset(lib, x2, y2)
    assert lib.LGBM_BoosterResetTrainingData(bst, ds2) == 0, _err(lib)
    fin = ctypes.c_int(0)
    assert lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0
    it = ctypes.c_int(0)
    assert lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)) == 0
    assert it.value == 4

    # leaf-pred refit: leaves of the current model on the training data
    n = len(y2)
    total = ctypes.c_int(0)
    assert lib.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(total)) == 0
    leaf_buf = np.zeros((n, total.value), np.float64)
    out_len = ctypes.c_int64(0)
    rc = lib.LGBM_BoosterPredictForMat(
        bst, x2.ctypes.data_as(ctypes.c_void_p), F64, n, x2.shape[1], 1,
        2, 0, -1, b"", ctypes.byref(out_len),
        leaf_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, _err(lib)
    leaves = np.ascontiguousarray(leaf_buf.astype(np.int32))
    lv_before = ctypes.c_double(0.0)
    assert lib.LGBM_BoosterGetLeafValue(bst, 0, 1,
                                        ctypes.byref(lv_before)) == 0
    rc = lib.LGBM_BoosterRefit(
        bst, leaves.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n, total.value)
    assert rc == 0, _err(lib)
    lv_after = ctypes.c_double(0.0)
    assert lib.LGBM_BoosterGetLeafValue(bst, 0, 1,
                                        ctypes.byref(lv_after)) == 0
    assert lv_after.value != lv_before.value
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds2)
    lib.LGBM_DatasetFree(ds)


def test_predict_csc_mats_and_fast_csr(lib):
    from scipy.sparse import csc_matrix
    x, y = _data(seed=11)
    ds = _make_dataset(lib, x, y)
    bst = _make_booster(lib, ds, iters=4)
    xt = np.ascontiguousarray(x[:20])
    want = np.zeros(20, np.float64)
    out_len = ctypes.c_int64(0)
    assert lib.LGBM_BoosterPredictForMat(
        bst, xt.ctypes.data_as(ctypes.c_void_p), F64, 20, xt.shape[1], 1,
        1, 0, -1, b"", ctypes.byref(out_len),
        want.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0

    # CSC
    csc = csc_matrix(xt)
    colptr = csc.indptr.astype(np.int32)
    got = np.zeros(20, np.float64)
    rc = lib.LGBM_BoosterPredictForCSC(
        bst, colptr.ctypes.data_as(ctypes.c_void_p), I32,
        csc.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        csc.data.ctypes.data_as(ctypes.c_void_p), F64,
        ctypes.c_int64(len(colptr)), ctypes.c_int64(csc.nnz),
        ctypes.c_int64(20), 1, 0, -1, b"",
        ctypes.byref(out_len),
        got.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, _err(lib)
    np.testing.assert_allclose(got, want, rtol=1e-9)

    # Mats (array of row pointers)
    rows = [np.ascontiguousarray(r) for r in xt]
    ptrs = (ctypes.c_void_p * 20)(
        *[r.ctypes.data_as(ctypes.c_void_p).value for r in rows])
    got2 = np.zeros(20, np.float64)
    rc = lib.LGBM_BoosterPredictForMats(
        bst, ptrs, F64, 20, xt.shape[1], 1, 0, -1, b"",
        ctypes.byref(out_len),
        got2.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, _err(lib)
    np.testing.assert_allclose(got2, want, rtol=1e-9)

    # CSR single-row FastConfig
    fc = ctypes.c_void_p()
    rc = lib.LGBM_BoosterPredictForCSRSingleRowFastInit(
        bst, 1, 0, -1, F64, ctypes.c_int64(xt.shape[1]), b"",
        ctypes.byref(fc))
    assert rc == 0, _err(lib)
    from scipy.sparse import csr_matrix
    one = csr_matrix(xt[:1])
    indptr = one.indptr.astype(np.int32)
    got3 = np.zeros(1, np.float64)
    rc = lib.LGBM_BoosterPredictForCSRSingleRowFast(
        fc, indptr.ctypes.data_as(ctypes.c_void_p), I32,
        one.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        one.data.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(one.nnz),
        ctypes.byref(out_len),
        got3.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, _err(lib)
    np.testing.assert_allclose(got3[0], want[0], rtol=1e-9)
    assert lib.LGBM_FastConfigFree(fc) == 0
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_predict_sparse_output(lib):
    from scipy.sparse import csr_matrix
    x, y = _data(seed=12)
    ds = _make_dataset(lib, x, y)
    bst = _make_booster(lib, ds, iters=3)
    xt = csr_matrix(np.ascontiguousarray(x[:8]))
    indptr = xt.indptr.astype(np.int32)
    out_len = (ctypes.c_int64 * 2)(0, 0)
    o_ip = ctypes.c_void_p()
    o_ix = ctypes.POINTER(ctypes.c_int32)()
    o_dt = ctypes.c_void_p()
    rc = lib.LGBM_BoosterPredictSparseOutput(
        bst, indptr.ctypes.data_as(ctypes.c_void_p), I32,
        xt.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        xt.data.ctypes.data_as(ctypes.c_void_p), F64,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(xt.nnz),
        ctypes.c_int64(x.shape[1]), 3, 0, -1, b"", 0,
        out_len, ctypes.byref(o_ip), ctypes.byref(o_ix),
        ctypes.byref(o_dt))
    assert rc == 0, _err(lib)
    nnz, n_indptr = out_len[0], out_len[1]
    assert n_indptr == 9                # 8 rows + 1
    # output buffers are typed like the INPUT (reference contract,
    # c_api.cpp:504-507): int32 indptr in -> int32 indptr out
    ip = np.ctypeslib.as_array(
        ctypes.cast(o_ip, ctypes.POINTER(ctypes.c_int32)), (n_indptr,))
    dt = np.ctypeslib.as_array(
        ctypes.cast(o_dt, ctypes.POINTER(ctypes.c_double)), (nnz,))
    assert ip[-1] == nnz
    # row contrib sums (incl. bias) must equal raw predictions
    want = np.zeros(8, np.float64)
    olen = ctypes.c_int64(0)
    xd = np.ascontiguousarray(x[:8])
    assert lib.LGBM_BoosterPredictForMat(
        bst, xd.ctypes.data_as(ctypes.c_void_p), F64, 8, x.shape[1], 1,
        1, 0, -1, b"", ctypes.byref(olen),
        want.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    sums = np.add.reduceat(dt, ip[:-1]) if nnz else np.zeros(8)
    np.testing.assert_allclose(sums, want, rtol=1e-6, atol=1e-9)
    assert lib.LGBM_BoosterFreePredictSparse(
        o_ip, o_ix, o_dt, I32, F64) == 0
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_register_log_callback(lib):
    seen = []
    CB = ctypes.CFUNCTYPE(None, ctypes.c_char_p)
    cb = CB(lambda msg: seen.append(msg))
    assert lib.LGBM_RegisterLogCallback(cb) == 0, _err(lib)
    x, y = _data(100, 3, seed=13)
    # an unknown parameter warns through Log -> must reach the C callback
    ds = _make_dataset(lib, x, y,
                       params=b"max_bin=15 zz_log_cb_probe=1")
    nd = ctypes.c_int(0)
    lib.LGBM_DatasetGetNumData(ds, ctypes.byref(nd))
    # unregister and make sure the hook held log output
    assert lib.LGBM_RegisterLogCallback(None) == 0
    lib.LGBM_DatasetFree(ds)
    assert any(b"zz_log_cb_probe" in m for m in seen), \
        f"warning did not reach the registered callback: {seen}"


def test_reference_abi_complete(lib):
    """Every LIGHTGBM_C_EXPORT symbol in the reference's c_api.h resolves
    in libcapi_train.so — the full-surface closure gate."""
    import re
    hdr = "/root/reference/include/LightGBM/c_api.h"
    if not os.path.exists(hdr):
        pytest.skip("reference header unavailable")
    names = set(re.findall(r"LIGHTGBM_C_EXPORT\s+[\w* ]+?(LGBM_\w+)",
                           open(hdr).read()))
    missing = [n for n in sorted(names) if not hasattr(lib, n)]
    assert not missing, f"unexported reference entry points: {missing}"
    assert len(names) >= 75


def test_predict_for_mats_colmajor_and_csr_single_row(lib):
    x, y = _data(seed=14)
    ds = _make_dataset(lib, x, y)
    bst = _make_booster(lib, ds, iters=3)
    xt = np.ascontiguousarray(x[:5])
    want = np.zeros(5, np.float64)
    out_len = ctypes.c_int64(0)
    assert lib.LGBM_BoosterPredictForMat(
        bst, xt.ctypes.data_as(ctypes.c_void_p), F64, 5, xt.shape[1], 1,
        1, 0, -1, b"", ctypes.byref(out_len),
        want.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    # typed CSR single-row (reference prototype, c_api.h:918)
    from scipy.sparse import csr_matrix
    one = csr_matrix(xt[:1])
    indptr = one.indptr.astype(np.int32)
    got = np.zeros(1, np.float64)
    rc = lib.LGBM_BoosterPredictForCSRSingleRow(
        bst, indptr.ctypes.data_as(ctypes.c_void_p), I32,
        one.indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        one.data.ctypes.data_as(ctypes.c_void_p), F64,
        ctypes.c_int64(len(indptr)), ctypes.c_int64(one.nnz),
        ctypes.c_int64(xt.shape[1]), 1, 0, -1, b"",
        ctypes.byref(out_len),
        got.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0, _err(lib)
    np.testing.assert_allclose(got[0], want[0], rtol=1e-9)
    # CreateFromMats: two blocks == one dataset of the concatenation
    blocks = [np.ascontiguousarray(x[:200]), np.ascontiguousarray(x[200:])]
    ptrs = (ctypes.c_void_p * 2)(
        *[b.ctypes.data_as(ctypes.c_void_p).value for b in blocks])
    nrows = (ctypes.c_int32 * 2)(200, len(x) - 200)
    ds2 = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromMats(
        2, ptrs, F64, nrows, x.shape[1], 1, b"max_bin=31 verbosity=-1",
        None, ctypes.byref(ds2))
    assert rc == 0, _err(lib)
    nd = ctypes.c_int(0)
    assert lib.LGBM_DatasetGetNumData(ds2, ctypes.byref(nd)) == 0
    assert nd.value == len(x)
    lib.LGBM_DatasetFree(ds2)
    lib.LGBM_BoosterFree(bst)
    lib.LGBM_DatasetFree(ds)


def test_network_init_with_functions(lib):
    assert lib.LGBM_NetworkInitWithFunctions(1, 0, None, None) == 0
    assert lib.LGBM_NetworkInitWithFunctions(
        2, 0, ctypes.c_void_p(0xdead), None) == -1
    assert b"XLA" in _err(lib)
