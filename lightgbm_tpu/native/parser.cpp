// Native CSV/TSV parser: the data-loading fast path.
//
// TPU-native equivalent of the reference's C++ text parsing layer
// (/root/reference/src/io/parser.cpp CSVParser/TSVParser +
// dataset_loader.cpp LoadTextDataToMemory): mmap the file, split line
// ranges across OpenMP threads, strtod each field into a dense row-major
// double matrix. Exposed through ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC parser.cpp -o libparser.so

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

struct MappedFile {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;
  bool ok() const { return data != nullptr; }
  explicit MappedFile(const char* path) {
    fd = open(path, O_RDONLY);
    if (fd < 0) return;
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size == 0) { close(fd); fd = -1; return; }
    size = static_cast<size_t>(st.st_size);
    void* p = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
    if (p == MAP_FAILED) { close(fd); fd = -1; return; }
    data = static_cast<const char*>(p);
  }
  ~MappedFile() {
    if (data) munmap(const_cast<char*>(data), size);
    if (fd >= 0) close(fd);
  }
};

// index of the first character after each newline (line starts)
std::vector<size_t> line_starts(const char* d, size_t n, int skip_header) {
  std::vector<size_t> starts;
  starts.push_back(0);
  for (size_t i = 0; i < n; ++i) {
    if (d[i] == '\n' && i + 1 < n) starts.push_back(i + 1);
  }
  // drop empty trailing lines
  while (!starts.empty()) {
    size_t s = starts.back();
    size_t e = s;
    while (e < n && d[e] != '\n') ++e;
    bool empty = true;
    for (size_t j = s; j < e; ++j)
      if (d[j] != ' ' && d[j] != '\r' && d[j] != '\t') { empty = false; break; }
    if (empty) starts.pop_back(); else break;
  }
  if (skip_header && !starts.empty()) starts.erase(starts.begin());
  return starts;
}

long count_cols(const char* d, size_t start, size_t n, char delim) {
  long cols = 1;
  for (size_t i = start; i < n && d[i] != '\n'; ++i)
    if (d[i] == delim) ++cols;
  return cols;
}

}  // namespace

extern "C" {

// Probe pass: number of data rows and columns. Returns 0 on success.
long lgbt_csv_shape(const char* path, char delim, int skip_header,
                    long* rows, long* cols) {
  MappedFile f(path);
  if (!f.ok()) return -1;
  auto starts = line_starts(f.data, f.size, skip_header);
  *rows = static_cast<long>(starts.size());
  *cols = starts.empty() ? 0 : count_cols(f.data, starts[0], f.size, delim);
  return 0;
}

// Parse pass: fill a rows*cols row-major double matrix. Missing fields and
// unparsable tokens become NaN (reference missing semantics). Returns 0 on
// success.
long lgbt_csv_parse(const char* path, char delim, int skip_header,
                    double* out, long rows, long cols) {
  MappedFile f(path);
  if (!f.ok()) return -1;
  auto starts = line_starts(f.data, f.size, skip_header);
  if (static_cast<long>(starts.size()) < rows) return -2;
  const char* d = f.data;
  const size_t n = f.size;
  const double kNaN = strtod("nan", nullptr);

#pragma omp parallel for schedule(static)
  for (long r = 0; r < rows; ++r) {
    size_t p = starts[r];
    double* row = out + r * cols;
    for (long c = 0; c < cols; ++c) {
      // empty field or line end -> NaN
      if (p >= n || d[p] == '\n' || d[p] == delim) {
        row[c] = kNaN;
        if (p < n && d[p] == delim) ++p;
        continue;
      }
      char* end = nullptr;
      double v = strtod(d + p, &end);
      if (end == d + p) {
        row[c] = kNaN;  // unparsable token (e.g. "na")
        while (p < n && d[p] != delim && d[p] != '\n') ++p;
      } else {
        row[c] = v;
        p = static_cast<size_t>(end - d);
        while (p < n && d[p] != delim && d[p] != '\n' && d[p] != '\r') ++p;
      }
      if (p < n && d[p] == delim) ++p;
    }
    // skip to end of line for safety
  }
  return 0;
}

}  // extern "C"
