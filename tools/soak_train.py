"""Chaos-injection soak harness for ELASTIC TRAINING (the
``tools/soak_serve.py`` analog for the training side).

Runs one boosting job under the elastic recovery ladder
(``lightgbm_tpu/parallel/elastic.elastic_train``) while
``utils/faultinject`` windows wedge its collectives
(``collective_hang``), wedge its device claim (``claim_wedge``) and
kill a simulated peer (``host_loss``) mid-run, then checks the
invariants the elastic layer promises (docs/Fault-Tolerance.md
"Elastic training"):

- **Zero hangs**: every collective is bounded by
  ``elastic_collective_timeout_s`` — the injected wedges sleep far
  longer than the deadline, so the run only completes inside the
  wall-clock budget if the deadline actually fired and classified
  every one of them.
- **Shrink-to-survive**: the run completes WITH at least one mesh
  shrink (full mesh -> shrunk mesh -> serial as the chaos demands),
  resuming each rung from the newest COMPLETE snapshot — no lost
  iterations beyond the snapshot gap, counted via the final model's
  tree count.
- **Determinism**: the final model passes the metric-parity harness
  against an uninterrupted SERIAL run over the same data — bitwise
  tree text on the int32 quantized-histogram path (the default here),
  metric-epsilon on f32.
- **Observability**: ``elastic.*`` recovery metrics are present
  (failures by kind, shrinks, recoveries, mesh gauge), the
  per-failure JSONL event log exists next to the model, and the
  flight recorder (``telemetry_blackbox``) dumped on the classified
  failures.

The ``sdc=1`` mode swaps the liveness chaos for SILENT-data-corruption
chaos (lightgbm_tpu/integrity.py; docs/Fault-Tolerance.md layer 7):
seeded single-bit flips at the ``hist_sdc``/``score_sdc`` sites put one
TRANSIENT flip (re-check clean -> absorbed in place, no rewind) and one
STICKY flip (fires again on the re-check -> classified ``sdc``, suspect
device quarantined, ladder rewinds to the newest integrity-VERIFIED
snapshot) into a single run — which must still end byte-identical to an
uninjected reference.

Run standalone (prints one JSON report, exit 1 on violations)::

    python tools/soak_train.py rounds=16 mesh=4 chaos=1
    python tools/soak_train.py rounds=12 sdc=1

Importable: ``run_soak_train(...)`` returns the report dict —
``tests/test_zelastic.py`` (liveness) and ``tests/test_integrity.py``
(sdc) each run a short deterministic soak in tier-1.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time
from typing import Dict, Optional

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

N_FEAT = 6


def _data(n_rows: int, seed: int = 0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n_rows, N_FEAT)
    y = (x[:, 0] - 0.7 * x[:, 1] + 0.25 * rs.randn(n_rows) > 0) \
        .astype("float32")
    return x, y


def run_soak_train(rounds: int = 12, n_rows: int = 400, mesh: int = 4,
                   seed: int = 0, chaos: bool = True,
                   chaos_spec: Optional[str] = None,
                   quant: bool = True, workdir: Optional[str] = None,
                   hang_s: float = 6.0,
                   collective_timeout_s: float = 1.0,
                   budget_s: float = 300.0, sdc: bool = False,
                   params: Optional[Dict] = None) -> Dict:
    """One elastic-training soak; returns the report dict (module
    docstring).  ``chaos=False`` is the control arm: same config, no
    faults — must complete with zero shrinks and the same final model.
    ``sdc=True`` runs the silent-data-corruption arm instead: serial
    masked learner under the elastic ladder, one transient + one sticky
    bit flip, ``integrity_policy=quarantine``.
    """
    import tempfile

    from lightgbm_tpu import Dataset, train as engine_train
    from lightgbm_tpu import integrity
    from lightgbm_tpu.metrics import _auc
    from lightgbm_tpu.parallel import elastic
    from lightgbm_tpu.utils import faultinject

    workdir = workdir or tempfile.mkdtemp(prefix="lgbm_soak_train_")
    os.makedirs(workdir, exist_ok=True)
    out_model = os.path.join(workdir, "soak_model.txt")
    x, y = _data(n_rows, seed)

    p = {"objective": "binary", "num_leaves": 8, "max_bin": 31,
         "min_data_in_leaf": 5, "verbosity": -1,
         "tree_learner": "data", "mesh_shape": [int(mesh)],
         "quant_train": bool(quant),
         "output_model": out_model,
         "snapshot_freq": 2, "snapshot_keep": 0,
         "elastic_enable": True,
         "elastic_collective_timeout_s": float(collective_timeout_s),
         "elastic_retries": 1,
         "elastic_recover_timeout_s": float(budget_s),
         "dist_init_timeout_s": float(collective_timeout_s),
         "dist_init_retries": 0,
         "telemetry_blackbox": True}
    if sdc:
        # SDC arm: serial masked learner (the integrity layer's shadow
        # grower is an independent trace there), every iteration
        # shadow-checked, sticky failures quarantined so the ladder —
        # not engine.train's own rewind loop — drives the recovery
        p.pop("tree_learner", None)
        p.pop("mesh_shape", None)
        p["tpu_learner"] = "masked"
        p["integrity_check_freq"] = 1
        p["integrity_policy"] = "quarantine"
    p.update(params or {})

    # uninterrupted SERIAL oracle over the same data — the parity
    # anchor the shrunk/ recovered run must reproduce
    ref_params = {k: v for k, v in p.items()
                  if not k.startswith(("elastic_", "dist_init",
                                       "telemetry", "snapshot",
                                       "mesh_shape", "output_model"))}
    ref_params["tree_learner"] = "serial"
    ref = engine_train(dict(ref_params), Dataset(x, label=y),
                       num_boost_round=rounds)

    violations = []
    if sdc:
        # one TRANSIENT (score gather, iteration 3: fires once, the
        # re-check hit does not -> absorbed) and one STICKY window
        # (histogram, 3 consecutive hits: fire + re-check fire ->
        # sticky -> ladder rewind, then the replay's fire re-checks
        # clean -> absorbed) in a single run
        s0 = max(4, int(rounds) - 5)
        spec = chaos_spec or (f"score_sdc:3,hist_sdc:{s0}-{s0 + 2}"
                              if chaos else None)
    else:
        spec = chaos_spec or ("collective_hang:4,claim_wedge:2,"
                              "host_loss:8" if chaos else None)
    prev_hang = os.environ.get(faultinject.HANG_ENV_VAR)
    os.environ[faultinject.HANG_ENV_VAR] = str(hang_s)
    elastic.reset_metrics()
    integrity.reset_metrics()
    t0 = time.monotonic()
    try:
        faultinject.configure(spec)
        bst = elastic.elastic_train(dict(p), x, y,
                                    num_boost_round=rounds)
    finally:
        faultinject.clear()
        if prev_hang is None:
            os.environ.pop(faultinject.HANG_ENV_VAR, None)
        else:
            os.environ[faultinject.HANG_ENV_VAR] = prev_hang
    wall_s = time.monotonic() - t0
    report = dict(bst.elastic_report)
    metrics = elastic.metrics_snapshot()

    # -- invariants --------------------------------------------------------
    if wall_s > budget_s:
        violations.append(
            f"run exceeded its wall budget ({wall_s:.1f}s > {budget_s}s):"
            " a collective was NOT bounded by the deadline")
    n_trees = len(bst.trees)
    if n_trees != rounds:
        violations.append(
            f"lost iterations: {n_trees} trees != {rounds} requested "
            "(recovery must lose nothing beyond the snapshot gap, which "
            "is retrained on resume)")
    trees_of = (lambda b:
                b.model_to_string().split("parameters:")[0]
                .split("feature_infos")[1])
    if quant:
        if trees_of(bst) != trees_of(ref):
            violations.append(
                "final model is not bitwise-identical to the "
                "uninterrupted serial run (int32 quantized path)")
    auc_ref = _auc(y, ref.predict(x, raw_score=True), None)
    auc_got = _auc(y, bst.predict(x, raw_score=True), None)
    if abs(float(auc_ref) - float(auc_got)) > 1e-6:
        violations.append(
            f"metric parity failed: soak auc {auc_got:.6f} vs "
            f"serial {auc_ref:.6f}")
    int_metrics = {k: v.get("value")
                   for k, v in integrity.metrics_snapshot().items()
                   if v.get("type") != "histogram"}
    if chaos:
        if report.get("shrinks", 0) < 1:
            violations.append("chaos run finished without a mesh shrink")
        if report.get("recoveries", 0) < 1:
            violations.append("no automatic recovery recorded")
        kinds = {f["kind"] for f in report.get("failures", ())}
        if not kinds:
            violations.append("no classified failures recorded")
        if sdc:
            if kinds != {"sdc"}:
                violations.append(
                    f"expected only classified 'sdc' failures, got {kinds}")
            if int_metrics.get("integrity.sticky", 0) != 1:
                violations.append(
                    "exactly one sticky SDC expected, got "
                    f"{int_metrics.get('integrity.sticky', 0)}")
            if int_metrics.get("integrity.transient_absorbed", 0) < 2:
                violations.append(
                    "transient SDCs (score @3 + post-rewind replay) were "
                    "not absorbed in place: "
                    f"{int_metrics.get('integrity.transient_absorbed', 0)}")
            if int_metrics.get("integrity.quarantined", 0) < 1:
                violations.append("sticky SDC did not quarantine a device")
            if not elastic.suspected_devices():
                violations.append("no suspect device recorded after the "
                                  "sticky SDC")
        if not any(k.startswith("elastic.failures")
                   for k in metrics):
            violations.append("elastic.failures metrics missing")
        if "elastic.shrinks" not in metrics:
            violations.append("elastic.shrinks metric missing")
        if not os.path.exists(out_model + ".elastic.jsonl"):
            violations.append("elastic failure event log missing")
        bb = glob.glob(os.path.join(workdir, "*.blackbox.jsonl*"))
        if not bb:
            violations.append("no flight-recorder (blackbox) dump found")
    else:
        if report.get("shrinks", 0) != 0:
            violations.append("control run shrank without chaos")

    return {"violations": violations, "wall_s": round(wall_s, 2),
            "rounds": rounds, "n_trees": n_trees,
            "report": report,
            "auc": round(float(auc_got), 6),
            "elastic_metrics": {k: v.get("value")
                                for k, v in metrics.items()
                                if v.get("type") != "histogram"},
            "integrity_metrics": int_metrics,
            "workdir": workdir}


def main(argv) -> int:
    kv = dict(a.split("=", 1) for a in argv if "=" in a)
    # force CPU + a virtual multi-device topology the supported way
    # (the axon sitecustomize freezes jax_platforms at interpreter
    # start; same pattern as bench.py / tools/check_retraces.py)
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_"
                                     "device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    rep = run_soak_train(
        rounds=int(kv.get("rounds", 12)),
        n_rows=int(kv.get("rows", 400)),
        mesh=int(kv.get("mesh", 4)),
        chaos=kv.get("chaos", "1") not in ("0", "false"),
        quant=kv.get("quant", "1") not in ("0", "false"),
        hang_s=float(kv.get("hang_s", 6.0)),
        budget_s=float(kv.get("budget_s", 300.0)),
        sdc=kv.get("sdc", "0") not in ("0", "false"))
    print(json.dumps(rep, indent=1, sort_keys=True))
    return 1 if rep["violations"] else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
