"""Opt-in ``jax.profiler`` capture of a training-iteration window.

``telemetry_profile_iters=[k, n]`` captures iterations [k, k+n) into a
TensorBoard-loadable trace directory.  The window is driven by the GBDT
iteration loop (models/gbdt.py) through ``on_iter_begin``/``on_iter_end``
so the capture brackets exactly the requested iterations — including
their compile, if iteration k is the first of a new jitted shape.

The capture is best-effort by design: profiler availability differs per
backend (the axon tunnel has no profiler service), and a failed start
must never kill a training run — failures are logged once and the
window deactivates itself.
"""

from __future__ import annotations

import atexit


class ProfilerWindow:
    """Capture iterations [start, start + count) with jax.profiler."""

    def __init__(self, start: int, count: int, logdir: str):
        self.start = int(start)
        self.count = max(int(count), 1)
        self.logdir = logdir
        self.active = False
        self._dead = False        # start failed: stay off for the run

    def on_iter_begin(self, it: int) -> None:
        if self._dead or self.active or it != self.start:
            return
        try:
            import jax.profiler
            jax.profiler.start_trace(self.logdir)
            self.active = True
            # a crash inside the window must still flush the capture
            atexit.register(self.finish)
            from ..utils.log import Log
            Log.info(f"telemetry: jax.profiler capturing iterations "
                     f"[{self.start}, {self.start + self.count}) -> "
                     f"{self.logdir}")
        except Exception as e:   # no profiler on this backend
            self._dead = True
            from ..utils.log import Log
            Log.warning(f"telemetry: jax.profiler capture unavailable "
                        f"({e}); continuing without it")

    def on_iter_end(self, it: int) -> None:
        if self.active and it + 1 >= self.start + self.count:
            self.finish()

    def finish(self) -> None:
        if not self.active:
            return
        self.active = False
        try:
            import jax.profiler
            jax.profiler.stop_trace()
        except Exception as e:
            from ..utils.log import Log
            Log.warning(f"telemetry: jax.profiler stop failed ({e})")
