"""Tier-1 (short) run of the ingest chaos soak (tools/soak_ingest.py).

One deterministic pass with all three injected failure kinds — transient
read error, corrupt chunk, reader hang — plus the no-chaos control arm.
The full-length soak is the standalone tool; this keeps its invariants
(quarantine accounting, bounded wall clock, resume/heal parity) in every
tier-1 run.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "tools"))

from soak_ingest import run_soak_ingest  # noqa: E402


def test_soak_ingest_chaos_short(tmp_path):
    rep = run_soak_ingest(n_rows=1600, chunk_rows=200, rounds=3,
                          chaos=True, hang_s=6.0, budget_s=90.0,
                          workdir=str(tmp_path))
    assert rep["violations"] == []
    assert rep["report"]["dropped_rows"] == 200
    assert len(rep["report"]["quarantined"]) == 1


@pytest.mark.slow
def test_soak_ingest_control(tmp_path):
    rep = run_soak_ingest(n_rows=1000, chunk_rows=250, rounds=3,
                          chaos=False, workdir=str(tmp_path))
    assert rep["violations"] == []
