"""Native (C++) runtime components, loaded via ctypes.

The reference's runtime is C++ end-to-end; here the TPU compute path is
JAX/XLA and the host-side hot paths that remain native are implemented in
C++ and bound with ctypes (no pybind11 in the image): currently the text
parser (parser.cpp — src/io/parser.cpp analog).  Binaries are built on
first use with g++ and cached next to the sources.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libparser.so")
_SRC = os.path.join(_DIR, "parser.cpp")
_lock = threading.Lock()
_lib = None
_lib_failed = False


def build_lib(src: str, so: str) -> bool:
    """Compile one C++ source into a shared library (OpenMP if available)."""
    cmds = [
        ["g++", "-O3", "-fopenmp", "-shared", "-fPIC", src, "-o", so],
        ["g++", "-O3", "-shared", "-fPIC", src, "-o", so],  # no-omp fallback
    ]
    for cmd in cmds:
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
            if r.returncode == 0 and os.path.exists(so):
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def load_lib(src_name: str, so_name: str) -> Optional[ctypes.CDLL]:
    """Build-on-first-use + dlopen for a native component next to this
    package; returns None when the toolchain is unavailable."""
    src = os.path.join(_DIR, src_name)
    so = os.path.join(_DIR, so_name)
    if not os.path.exists(so) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(so)):
        if not build_lib(src, so):
            return None
    try:
        return ctypes.CDLL(so)
    except OSError:
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        lib = load_lib(os.path.basename(_SRC), os.path.basename(_SO))
        if lib is None:
            _lib_failed = True
            return None
        try:
            lib.lgbt_csv_shape.restype = ctypes.c_long
            lib.lgbt_csv_shape.argtypes = [
                ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
            lib.lgbt_csv_parse.restype = ctypes.c_long
            lib.lgbt_csv_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                ctypes.c_long, ctypes.c_long]
            _lib = lib
        except OSError:
            _lib_failed = True
    return _lib


def native_parse_csv(path: str, delim: str = ",",
                     has_header: bool = False) -> Optional[np.ndarray]:
    """Parse a CSV/TSV file into [rows, cols] float64; None if the native
    library is unavailable (caller falls back to NumPy)."""
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    rc = lib.lgbt_csv_shape(path.encode(), delim.encode(),
                            int(has_header), ctypes.byref(rows),
                            ctypes.byref(cols))
    if rc != 0 or rows.value <= 0 or cols.value <= 0:
        return None
    out = np.empty((rows.value, cols.value), np.float64)
    rc = lib.lgbt_csv_parse(path.encode(), delim.encode(), int(has_header),
                            out, rows.value, cols.value)
    if rc != 0:
        return None
    return out
