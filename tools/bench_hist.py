"""Microbenchmark histogram formulations on the real TPU.

The chip is behind a tunnel with a ~30-70 ms per-call latency floor, so each
variant is applied R times IN-GRAPH (chained through a dummy dependency) and
we report device-time-per-pass = wall / R.

Run: python tools/bench_hist.py [n_rows] [R]

--quant {off,8,16}: quantized-training sweep instead — the SHIPPED
``compute_histogram`` (f32 vs int8/int16 packed accumulands,
ops/quantize.py) across split_batch-shaped slot widths K in {16,32,64},
reporting ms/pass, achieved TFLOP/s, and the static per-pass HBM bytes
from the shared ledger formula (obs/flops.py).  ``run_quant_bench`` is
the importable entry bench.py folds into its extras as ``hist_quant_*``
keys.  Default (no value) runs all three.

Run: python tools/bench_hist.py --quant [8] [n_rows] [R]

--sharded: microbench the data-parallel histogram REDUCTION instead —
owner-shard ``psum_scatter`` (each shard keeps [ceil(F/n), B, 3] of global
histograms) vs the legacy full ``psum`` ([F, B, 3] replicated to every
shard) at HIGGS (28) and Allstate (4228) feature widths over >= 2 shard
counts.  Reports ms/pass and per-shard histogram bytes as JSON lines,
with the measuring platform recorded in every record.  By default the
bench runs on a virtual 8-device CPU mesh (this host's TPU is a single
tunneled chip — no multi-device collective exists to measure);
``--sharded-tpu`` keeps the real backend instead for hosts that DO have
>= 2 accelerators, so recorded numbers are real ICI collectives there.
Per-shard byte counts are platform-independent either way.

Run: python tools/bench_hist.py --sharded [R] [--sharded-tpu]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")

SHARDED_REAL = "--sharded-tpu" in sys.argv
SHARDED = "--sharded" in sys.argv or SHARDED_REAL
if SHARDED and not SHARDED_REAL \
        and "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax
import jax.numpy as jnp
from jax import lax

if SHARDED and not SHARDED_REAL:
    jax.config.update("jax_platforms", "cpu")


def amortized(make_one, R):
    """make_one(binned, vals, salt) -> [F, B, 3]; returns jitted R-rep fn."""
    @jax.jit
    def rep(binned, vals):
        def body(i, acc):
            # salt the vals with i so XLA can't hoist the pass out of the loop
            h = make_one(binned, vals + (i * 1e-12), i)
            return acc + h
        return lax.fori_loop(0, R, body, jnp.zeros_like(make_one(binned, vals, 0)))
    return rep


def timeit(fn, *args, reps=3):
    # obs.trace.fence, NOT block_until_ready: the latter returns early
    # on the axon backend (PROFILE.md methodology / docs/Observability.md)
    from lightgbm_tpu.obs.trace import fence
    fence(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fence(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def hist_variant(block_rows, dtype, orient, num_bins, f):
    def one(binned, vals, salt):
        n = binned.shape[0]
        pad = (-n) % block_rows
        if pad:
            binned = jnp.pad(binned, ((0, pad), (0, 0)))
            vals = jnp.pad(vals, ((0, pad), (0, 0)))
        nblocks = (n + pad) // block_rows
        binned_b = binned.reshape(nblocks, block_rows, f)
        vals_b = vals.reshape(nblocks, block_rows, 3)
        iota = jnp.arange(num_bins, dtype=jnp.int32)

        def body(acc, chunk):
            bins_blk, vals_blk = chunk
            onehot = (bins_blk.astype(jnp.int32)[:, :, None] == iota) \
                .astype(dtype).reshape(block_rows, f * num_bins)
            if orient == "fb3":
                h = lax.dot_general(
                    onehot, vals_blk.astype(dtype),
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            else:
                h = lax.dot_general(
                    vals_blk.astype(dtype), onehot,
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32).T
            return acc + h, None

        acc0 = jnp.zeros((f * num_bins, 3), dtype=jnp.float32)
        acc, _ = lax.scan(body, acc0, (binned_b, vals_b))
        return acc.reshape(f, num_bins, 3)
    return one


def sharded_main():
    """Owner-shard ``psum_scatter`` vs full ``psum`` of the reduced
    histogram tensor (the dp learner's one heavy collective) — isolated
    from the histogram build so the Allstate width stays benchable on a
    CPU mesh.  Per-shard histogram bytes are the RESULT state each chip
    must hold per leaf: chunk*B*3*4 (owner-shard) vs F*B*3*4 (psum)."""
    import json

    from lightgbm_tpu.parallel import make_mesh, owner_shard_plan
    from lightgbm_tpu.parallel.data_parallel import owner_hist_reduce
    from lightgbm_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    args = [a for a in sys.argv[1:] if not a.startswith("--sharded")]
    R = int(args[0]) if args else 50
    platform = jax.devices()[0].platform
    B = 64
    widths = (("higgs", 28), ("allstate", 4228))
    shard_counts = [s for s in (2, 4, 8) if s <= len(jax.devices())]
    assert len(shard_counts) >= 2, \
        f"need >=2 benchable shard counts, have {len(jax.devices())} devices"

    for n_shards in shard_counts:
        mesh = make_mesh((n_shards,), ("data",),
                         jax.devices()[:n_shards])
        for name, f in widths:
            plan = owner_shard_plan(np.arange(f), n_shards)
            scatter_red = owner_hist_reduce("data", n_shards, plan.chunk)
            full_red = lambda h: lax.psum(h, "data")
            rng = np.random.RandomState(0)
            h_local = rng.rand(f, B, 3).astype(np.float32)

            def bench(red):
                def body(h):
                    def step(i, acc):
                        r = red(h + i * jnp.float32(1e-9))
                        return acc + lax.psum(r.sum(), "data")
                    return lax.fori_loop(0, R, step, jnp.float32(0.0))
                fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(),),
                                       out_specs=P(), check_vma=False))
                return timeit(fn, h_local) / R

            t_scatter = bench(scatter_red)
            t_psum = bench(full_red)
            rec = {
                "bench": "dp_hist_reduce", "platform": platform,
                "width": name, "F": f, "B": B,
                "n_shards": n_shards, "owner_chunk": plan.chunk,
                "per_shard_hist_bytes_owner": plan.hist_bytes(1, B),
                "per_shard_hist_bytes_psum": f * B * 3 * 4,
                "ms_per_pass_psum_scatter": round(t_scatter * 1e3, 3),
                "ms_per_pass_full_psum": round(t_psum * 1e3, 3),
            }
            print(json.dumps(rec), flush=True)
            print(f"  shards={n_shards} {name}(F={f}): owner-shard "
                  f"{rec['per_shard_hist_bytes_owner']/1e3:.1f} kB/shard "
                  f"@ {rec['ms_per_pass_psum_scatter']:.3f} ms vs full-psum "
                  f"{rec['per_shard_hist_bytes_psum']/1e3:.1f} kB/shard "
                  f"@ {rec['ms_per_pass_full_psum']:.3f} ms",
                  file=sys.stderr, flush=True)


def run_quant_bench(n_rows: int = 200_000, reps: int = 5,
                    quants=("off", "8", "16"), ks=(16, 32, 64),
                    f: int = 28, num_bins: int = 63,
                    tune: bool = True) -> dict:
    """Quantized-vs-f32 histogram contraction sweep over the
    split_batch slot widths K in {16, 32, 64} — the SHIPPED kernel
    (compute_histogram), not a bench-local variant, so dtype dispatch,
    block sizing (hist_block_rows by vals itemsize AND the wide
    channel/accumulator budget), the MXU lane padding of the wide
    widths (C=96 -> 128, C=192 -> 256) and the int32 accumulation are
    exactly what training runs.  Per width both the raw ``ms_per_pass``
    and the decision metric ``ms_per_leaf`` (= ms/pass / K — a wider
    pass may cost more wall and still win per split) are recorded;
    with ``tune`` the REAL autotuner (ops/hist_tune.py, in-memory
    table only — the bench must not poison the training cache) runs on
    the same shape and its chosen (K, block_rows) lands in the record
    as ``tuned_k`` / ``tuned_block_rows``.  Returns a flat dict
    bench.py folds into extras as ``hist_quant_<key>``."""
    import jax as _jax
    import jax.numpy as _jnp
    from lightgbm_tpu.obs.flops import hist_flops_bytes, padded_bins
    from lightgbm_tpu.obs.trace import fence
    from lightgbm_tpu.ops.histogram import compute_histogram
    from lightgbm_tpu.ops.quantize import (QuantSpec, quant_scales,
                                           quantize_stack)

    rng = np.random.RandomState(0)
    binned = _jnp.asarray(rng.randint(0, num_bins, size=(n_rows, f),
                                      dtype=np.uint8))
    vals_f32 = _jnp.asarray(rng.randn(n_rows, 3).astype(np.float32))
    out = {}
    for q in quants:
        if q == "off":
            vals, isz = vals_f32, 4
        else:
            spec = QuantSpec(bits=int(q))
            scales = quant_scales(vals_f32, spec.qmax)
            vals = quantize_stack(vals_f32, scales, spec,
                                  _jnp.int32(0), 0)
            isz = spec.itemsize
        for k in ks:
            slot = _jnp.asarray(
                rng.randint(0, k, size=n_rows, dtype=np.int32))

            @_jax.jit
            def rep(b, v, s, _k=k):
                def body(i, acc):
                    h = compute_histogram(b, v, num_bins=num_bins,
                                          slot=s + 0 * i, num_slots=_k)
                    return acc + h.astype(_jnp.float32)
                z = compute_histogram(b, v, num_bins=num_bins, slot=s,
                                      num_slots=_k)
                return lax.fori_loop(0, reps, body,
                                     jnp.zeros_like(z, jnp.float32))

            fence(rep(binned, vals, slot))
            t0 = time.perf_counter()
            fence(rep(binned, vals, slot))
            t = (time.perf_counter() - t0) / reps
            fl, hb = hist_flops_bytes(n_rows, f, num_bins,
                                      channels=3 * k, vals_itemsize=isz)
            out[f"q{q}_k{k}_ms_per_pass"] = round(t * 1e3, 3)
            out[f"q{q}_k{k}_ms_per_leaf"] = round(t * 1e3 / k, 4)
            out[f"q{q}_k{k}_tflops"] = round(fl / t / 1e12, 4)
            out[f"q{q}_k{k}_intensity"] = round(fl / hb, 2)
        _, hb1 = hist_flops_bytes(n_rows, f, num_bins, channels=3,
                                  vals_itemsize=isz)
        out[f"q{q}_hbm_bytes_per_pass"] = hb1
        if tune:
            # the autotuner's own verdict for this (shape, dtype): an
            # in-memory sweep (no table writes) so every bench point
            # carries the chosen (K, block_rows) as provenance
            try:
                from lightgbm_tpu.ops.hist_tune import tune as _tune
                rec = _tune(n_rows, f, num_bins, itemsize=isz,
                            kmax=max(ks), reps=max(2, reps // 2))
                out[f"q{q}_tuned_k"] = rec["k"]
                out[f"q{q}_tuned_block_rows"] = rec["block_rows"]
                if q == "off":
                    out["tuned_k"] = rec["k"]
                    out["tuned_block_rows"] = rec["block_rows"]
            except Exception as e:      # bench never dies on the tuner
                out[f"q{q}_tuned_error"] = f"{type(e).__name__}: {e}"[:80]
    out.update(n_rows=n_rows, f=f, num_bins=num_bins,
               padded_bins=padded_bins(num_bins), reps=reps)
    return out


def quant_main():
    import json
    args = [a for a in sys.argv[1:] if a != "--quant"]
    quants = ("off", "8", "16")
    if args and args[0] in ("off", "8", "16"):
        quants = (args.pop(0),)
    n = int(args[0]) if args else 200_000
    reps = int(args[1]) if len(args) > 1 else 5
    rec = run_quant_bench(n_rows=n, reps=reps, quants=quants)
    rec["bench"] = "hist_quant"
    rec["platform"] = jax.devices()[0].platform
    print(json.dumps(rec), flush=True)
    for k in sorted(rec):
        if k.endswith("_ms_per_pass"):
            print(f"  {k} = {rec[k]} ms "
                  f"({rec[k.replace('_ms_per_pass', '_tflops')]} TF/s)",
                  file=sys.stderr, flush=True)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    R = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    f, B = 28, 64
    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, B, size=(n, f), dtype=np.uint8))
    vals = jnp.asarray(rng.randn(n, 3).astype(np.float32))
    from lightgbm_tpu.obs.trace import fence
    fence((binned, vals))
    print(f"n={n} f={f} B={B} R={R}; flops/pass = {2*3*n*f*B/1e9:.1f} GFLOP",
          file=sys.stderr, flush=True)

    ref = None
    for block in (888, 8192, 32768, 131072):
        for dtype, dname in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
            for orient in ("fb3", "3fb"):
                one = hist_variant(block, dtype, orient, B, f)
                try:
                    fn = amortized(one, R)
                    t = timeit(fn, binned, vals) / R
                    out = np.asarray(one(jnp.asarray(binned), vals, 0))
                    if ref is None:
                        ref = out
                    err = np.abs(out - ref).max() / max(np.abs(ref).max(), 1)
                    gfs = 2 * 3 * n * f * B / t / 1e12
                    print(f"block={block:7d} {dname:4s} {orient}: "
                          f"{t*1e3:8.2f} ms/pass  {gfs:6.2f} TF/s  "
                          f"relerr={err:.2e}", file=sys.stderr, flush=True)
                except Exception as e:
                    print(f"block={block:7d} {dname:4s} {orient}: FAIL "
                          f"{type(e).__name__}: {str(e)[:100]}",
                          file=sys.stderr, flush=True)

    # child-pass strategies at 25% occupancy
    leaf_of_row = jnp.asarray((rng.rand(n) < 0.25).astype(np.int32))
    cap = max(1 << int(np.ceil(np.log2(max(n // 4, 1)))), 8)
    base = hist_variant(8192, jnp.float32, "fb3", B, f)

    def masked_one(binned, vals, salt):
        m = (leaf_of_row == 1).astype(vals.dtype)[:, None]
        return base(binned, vals * m, salt)

    def gathered_one(binned, vals, salt):
        idx = jnp.nonzero(leaf_of_row == 1, size=cap, fill_value=n)[0]
        safe = jnp.minimum(idx, n - 1)
        b_g = jnp.take(binned, safe, axis=0)
        v_g = jnp.take(vals, safe, axis=0) \
            * (idx < n)[:, None].astype(vals.dtype)
        return base(b_g, v_g, salt)

    tm = timeit(amortized(masked_one, R), binned, vals) / R
    tg = timeit(amortized(gathered_one, R), binned, vals) / R
    print(f"child 25%: masked-full {tm*1e3:.2f} ms vs gather(cap={cap}) "
          f"{tg*1e3:.2f} ms", file=sys.stderr, flush=True)

    # isolate nonzero / take / partition-style ops
    def nz_one(binned, vals, salt):
        idx = jnp.nonzero((leaf_of_row + 0 * salt) == 1, size=cap,
                          fill_value=n)[0]
        return idx.astype(jnp.float32).sum().reshape(1, 1, 1) \
            * jnp.ones((1, 1, 1))

    def take_one(binned, vals, salt):
        idx = (jnp.arange(cap) * 3 + salt) % n
        return jnp.take(binned, idx, axis=0).astype(jnp.float32) \
            .sum().reshape(1, 1, 1)

    def part_one(binned, vals, salt):
        fcol = jnp.take(binned, 3, axis=1).astype(jnp.int32)
        go_left = fcol <= (16 + salt * 0)
        out = jnp.where((leaf_of_row == 1) & (~go_left), 7, leaf_of_row)
        return out.astype(jnp.float32).sum().reshape(1, 1, 1)

    for name, one in (("nonzero", nz_one), ("take[cap,F]", take_one),
                      ("partition-update", part_one)):
        t = timeit(amortized(one, R), binned, vals) / R
        print(f"  {name}: {t*1e3:.3f} ms", file=sys.stderr, flush=True)


if __name__ == "__main__":
    if SHARDED:
        sharded_main()
    elif "--quant" in sys.argv:
        quant_main()
    else:
        main()
