"""Fault-tolerance suite (ISSUE 2): device-claim retry/backoff +
serial fallback, finite guards in the boosting loop, atomic snapshots
with auto-resume (crash+resume == train-straight, byte-identical), and
the named fault-injection sites that drive it all.

Every injection site (device claim, collective, snapshot write,
kill-before-rename, NaN grads) has a test proving its configured policy
(retry / fallback / skip / raise) engages — the acceptance bar of the
issue.  Injection specs are installed programmatically via
``faultinject.configure`` and always cleared by the autouse fixture.
"""

import glob
import logging
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.utils import faultinject
from lightgbm_tpu.utils.faultinject import InjectedFault, InjectedKill
from lightgbm_tpu.utils.resilience import (RetryPolicy, Watchdog,
                                           atomic_write,
                                           is_retryable_device_error,
                                           retry_call)

_rs = np.random.RandomState(7)
X = _rs.randn(600, 10)
Y = (2.0 * X[:, 0] - X[:, 1] + 0.1 * _rs.randn(600)).astype(np.float32)

BASE = {"objective": "regression", "num_leaves": 7, "max_bin": 31,
        "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _clear_faults():
    """No injection spec may leak between tests."""
    faultinject.clear()
    yield
    faultinject.clear()


def _ds():
    return lgb.Dataset(X, label=Y)


# ---------------------------------------------------------------------------
# resilience primitives
# ---------------------------------------------------------------------------

class TestRetryPrimitives:
    def test_classifier_retryable_vs_fatal(self):
        assert is_retryable_device_error(
            RuntimeError("UNAVAILABLE: claim hung"))
        assert is_retryable_device_error(
            OSError("connection refused by relay"))
        assert is_retryable_device_error(
            RuntimeError("DEADLINE_EXCEEDED: barrier timed out"))
        assert not is_retryable_device_error(TypeError("unavailable"))
        assert not is_retryable_device_error(ValueError("bad argument"))
        assert not is_retryable_device_error(
            RuntimeError("some unrelated assertion"))
        # InjectedFault deliberately matches the retryable patterns
        assert is_retryable_device_error(InjectedFault("device_claim", 1))

    def test_retry_succeeds_after_transient(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("UNAVAILABLE: transient")
            return "ok"

        out = retry_call(flaky, policy=RetryPolicy(max_attempts=4,
                                                   base_delay_s=0.001))
        assert out == "ok" and len(calls) == 3

    def test_fatal_error_not_retried(self):
        calls = []

        def broken():
            calls.append(1)
            raise TypeError("programming error")

        with pytest.raises(TypeError):
            retry_call(broken, policy=RetryPolicy(max_attempts=5,
                                                  base_delay_s=0.001))
        assert len(calls) == 1

    def test_attempts_exhausted_reraises_last(self):
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            retry_call(lambda: (_ for _ in ()).throw(
                RuntimeError("UNAVAILABLE")),
                policy=RetryPolicy(max_attempts=2, base_delay_s=0.001))

    def test_hard_deadline_stops_backoff(self):
        calls = []

        def always_down():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE")

        # first backoff (10 s) would blow the 0.2 s deadline -> exactly
        # one attempt, immediate re-raise instead of sleeping
        with pytest.raises(RuntimeError):
            retry_call(always_down,
                       policy=RetryPolicy(max_attempts=5, base_delay_s=10.0,
                                          deadline_s=0.2))
        assert len(calls) == 1

    def test_watchdog_arms_and_cancels(self):
        # smoke: arming must not dump for a fast call, and a zero
        # timeout must be a no-op
        with Watchdog(60.0, label="test"):
            pass
        with Watchdog(0.0, label="disabled"):
            pass


class TestFaultSpecParsing:
    def test_grammar(self):
        faultinject.configure("device_claim:1-2,nan_grads:3,"
                              "snapshot_write:4-:exit")
        assert faultinject.enabled()
        faultinject.clear()
        assert not faultinject.enabled()

    @pytest.mark.parametrize("bad", ["nope:1", "device_claim",
                                     "device_claim:0", "device_claim:2-1",
                                     "device_claim:1:explode"])
    def test_bad_specs_rejected(self, bad):
        with pytest.raises(ValueError):
            faultinject.configure(bad)
        faultinject.clear()

    def test_hit_window(self):
        faultinject.configure("collective:2-3")
        assert not faultinject.fires("collective")      # hit 1
        assert faultinject.fires("collective")          # hit 2
        assert faultinject.fires("collective")          # hit 3
        assert not faultinject.fires("collective")      # hit 4
        assert faultinject.hits("collective") == 4


# ---------------------------------------------------------------------------
# atomic persistence
# ---------------------------------------------------------------------------

class TestAtomicWrites:
    def test_kill_before_rename_preserves_old_file(self, tmp_path):
        path = str(tmp_path / "f.txt")
        atomic_write(path, "old contents")
        faultinject.configure("snapshot_kill:1")
        with pytest.raises(InjectedKill):
            atomic_write(path, "new contents")
        faultinject.clear()
        # old file intact; the temp debris a real crash leaves is ignored
        with open(path) as f:
            assert f.read() == "old contents"

    def test_save_model_atomic(self, tmp_path):
        bst = lgb.train(dict(BASE), _ds(), num_boost_round=2)
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        first = open(path).read()
        faultinject.configure("snapshot_kill:1")
        with pytest.raises(InjectedKill):
            bst.save_model(path)
        faultinject.clear()
        assert open(path).read() == first

    def test_save_binary_atomic_and_exact_filename(self, tmp_path):
        ds = _ds()
        ds.construct(lgb.Config(dict(BASE)))
        path = str(tmp_path / "cache.bin")
        ds.save_binary(path)
        assert os.path.exists(path)            # no surprise '.npz' suffix
        good = open(path, "rb").read()
        faultinject.configure("snapshot_kill:1")
        with pytest.raises(InjectedKill):
            ds.save_binary(path)
        faultinject.clear()
        assert open(path, "rb").read() == good
        assert lgb.Dataset.load_binary(path).num_data == len(X)

    def test_snapshot_parent_dir_created(self, tmp_path, monkeypatch):
        # a RELATIVE output_model in a fresh working dir used to make
        # every snapshot write raise (engine.py satellite)
        monkeypatch.chdir(tmp_path)
        p = dict(BASE, snapshot_freq=2, output_model="out/nested/m.txt")
        lgb.train(p, _ds(), num_boost_round=2)
        assert os.path.exists("out/nested/m.txt.snapshot_iter_2")


# ---------------------------------------------------------------------------
# injection sites: device claim (retry / fallback), collective (raise),
# snapshot write (skip)
# ---------------------------------------------------------------------------

class TestDeviceClaimSite:
    DP = dict(BASE, tree_learner="data", dist_init_timeout_s=5.0)

    def test_retry_engages_and_training_proceeds(self):
        faultinject.configure("device_claim:1-2")
        bst = lgb.train(dict(self.DP, dist_init_retries=3), _ds(),
                        num_boost_round=2)
        assert bst.num_trees() == 2
        # two injected failures + the successful third attempt
        assert faultinject.hits("device_claim") == 3

    def test_exhausted_retries_raise_without_fallback(self):
        faultinject.configure("device_claim:1-")
        with pytest.raises(InjectedFault):
            lgb.train(dict(self.DP, dist_init_retries=1), _ds(),
                      num_boost_round=2)

    def test_fallback_serial_degrades_gracefully(self, caplog):
        faultinject.configure("device_claim:1-")
        with caplog.at_level(logging.WARNING, logger="lightgbm_tpu"):
            bst = lgb.train(dict(self.DP, dist_init_retries=1,
                                 dist_fallback_serial=True), _ds(),
                            num_boost_round=2)
        assert bst.num_trees() == 2
        assert any("falling back to the serial learner" in r.message
                   for r in caplog.records)

    def test_launch_init_retries_then_single_process_fallback(self):
        from lightgbm_tpu.parallel import launch
        was_done = getattr(launch.init, "_done", False)
        launch.init._done = False
        try:
            faultinject.configure("device_claim:1-2")
            # after the injected transients pass, the real auto-detect
            # initialize fails fatally on this CPU harness and the
            # documented single-process fallback engages — the assertion
            # is that the RETRY layer ran first
            launch.init(retries=3, timeout_s=5.0)
            assert faultinject.hits("device_claim") == 3
        finally:
            launch.init._done = was_done


class TestCollectiveSite:
    def test_collective_failure_surfaces_promptly(self):
        faultinject.configure("collective:1")
        with pytest.raises(InjectedFault, match="collective"):
            lgb.train(dict(BASE, tree_learner="data"), _ds(),
                      num_boost_round=2)


class TestSnapshotWriteSite:
    def test_failed_snapshot_skips_and_training_survives(self, tmp_path,
                                                         caplog):
        out = str(tmp_path / "m.txt")
        faultinject.configure("snapshot_write:1-")
        with caplog.at_level(logging.WARNING, logger="lightgbm_tpu"):
            bst = lgb.train(dict(BASE, snapshot_freq=2, output_model=out),
                            _ds(), num_boost_round=5)
        assert bst.num_trees() == 5
        assert any("training continues" in r.message
                   for r in caplog.records)
        # atomicity: the failed writes left no partial snapshot files
        assert not [f for f in os.listdir(tmp_path)
                    if not f.endswith(".tmp")]


# ---------------------------------------------------------------------------
# finite guards
# ---------------------------------------------------------------------------

class TestFiniteGuard:
    P = dict(BASE, finite_check_freq=1)

    def test_nan_grads_raise(self):
        faultinject.configure("nan_grads:3")
        with pytest.raises(LightGBMError, match="iteration 3"):
            lgb.train(dict(self.P, finite_check_policy="raise"), _ds(),
                      num_boost_round=5)

    def test_nan_grads_skip_iter(self, caplog):
        faultinject.configure("nan_grads:3")
        with caplog.at_level(logging.WARNING, logger="lightgbm_tpu"):
            bst = lgb.train(dict(self.P, finite_check_policy="skip_iter"),
                            _ds(), num_boost_round=5)
        # the poisoned iteration contributes a zero stump; training
        # recovers (gradients are recomputed from the untouched score)
        leaves = [t.num_leaves for t in bst.trees]
        assert bst.num_trees() == 5
        assert leaves[2] == 1 and float(bst.trees[2].leaf_value[0]) == 0.0
        assert all(nl > 1 for i, nl in enumerate(leaves) if i != 2)
        assert np.isfinite(bst.predict(X[:16])).all()
        assert any("skip_iter" in r.message for r in caplog.records)
        # the skipped stump round-trips through model text
        reloaded = lgb.Booster(model_str=bst.model_to_string())
        assert reloaded.trees[2].num_leaves == 1

    def test_nan_grads_clamp_trains_through(self):
        faultinject.configure("nan_grads:3")
        bst = lgb.train(dict(self.P, finite_check_policy="clamp"), _ds(),
                        num_boost_round=5)
        assert all(t.num_leaves > 1 for t in bst.trees)
        assert np.isfinite(np.concatenate(
            [t.leaf_value for t in bst.trees])).all()

    def test_check_freq_cadence(self):
        # with freq=2 the checks run at iterations 2/4/6 only: a NaN at
        # a check iteration raises there; the same NaN at an off-cadence
        # iteration is freq>1's documented blind spot (on this learner
        # it degenerates to a harmless stump — NaN gains never win a
        # split — so training neither raises nor corrupts)
        faultinject.configure("nan_grads:4")
        with pytest.raises(LightGBMError, match="iteration 4"):
            lgb.train(dict(self.P, finite_check_freq=2,
                           finite_check_policy="raise"), _ds(),
                      num_boost_round=6)
        faultinject.clear()
        faultinject.configure("nan_grads:3")
        bst = lgb.train(dict(self.P, finite_check_freq=2,
                             finite_check_policy="raise"), _ds(),
                        num_boost_round=6)
        assert np.isfinite(np.concatenate(
            [t.leaf_value for t in bst.trees])).all()

    # -- fused-chunk compatibility (the guard flags ride the one host
    #    sync per chunk) — NaN is seeded into the device score because
    #    labels are AvoidInf-sanitized at ingestion ------------------------
    FUSED = dict(BASE, tpu_learner="masked", boost_from_average=False,
                 finite_check_freq=1)

    def _poisoned(self, policy, fused_chunk):
        import jax.numpy as jnp
        bst = lgb.Booster(params=dict(self.FUSED, fused_chunk=fused_chunk,
                                      finite_check_policy=policy),
                          train_set=_ds())
        bst._model.score = bst._model.score.at[0, 0].set(jnp.nan)
        return bst

    def test_fused_raise(self):
        bst = self._poisoned("raise", 8)
        assert bst.supports_fused()
        with pytest.raises(LightGBMError, match="iteration 1"):
            bst.update_chunk(8)

    def test_fused_skip_iter_stumps_then_heals(self):
        # iteration 1 trips the check -> zero stump AND the score carry
        # is sanitized, so iterations 2..8 recover and train real trees
        bst = self._poisoned("skip_iter", 8)
        stopped = bst.update_chunk(8)
        assert not stopped
        leaves = [t.num_leaves for t in bst.trees]
        assert leaves[0] == 1 and float(bst.trees[0].leaf_value[0]) == 0.0
        assert all(nl > 1 for nl in leaves[1:])
        # ...and the fused path matches the per-iteration path exactly
        bp = self._poisoned("skip_iter", 0)
        for _ in range(8):
            bp.update()

        def strip(s):
            return "\n".join(l for l in s.splitlines()
                             if not l.startswith("[fused_chunk:"))
        assert strip(bst.model_to_string()) == strip(bp.model_to_string())

    def test_fused_clamp_matches_per_iteration_clamp(self):
        bf = self._poisoned("clamp", 8)
        bf.update_chunk(8)
        bp = self._poisoned("clamp", 0)
        for _ in range(8):
            bp.update()

        def strip(s):     # fused_chunk is the one differing param line
            return "\n".join(l for l in s.splitlines()
                             if not l.startswith("[fused_chunk:"))
        assert strip(bf.model_to_string()) == strip(bp.model_to_string())
        assert all(t.num_leaves > 1 for t in bf.trees)


# ---------------------------------------------------------------------------
# crash/resume equivalence (the acceptance bar): kill-before-rename at the
# second snapshot, auto-resume from the first — byte-identical model text
# ---------------------------------------------------------------------------

CONFIGS = {
    "serial": {},
    "data_parallel": {"tree_learner": "data"},
    "ffrac_bagging": {"feature_fraction": 0.7, "bagging_fraction": 0.8,
                      "bagging_freq": 2},
    "goss": {"data_sample_strategy": "goss"},
}


class TestCrashResume:
    @pytest.mark.parametrize("cfg_name", list(CONFIGS))
    def test_kill_and_resume_byte_identical(self, cfg_name, tmp_path):
        out = str(tmp_path / "m.txt")
        p = dict(BASE, snapshot_freq=3, output_model=out,
                 **CONFIGS[cfg_name])
        straight = lgb.train(dict(p), _ds(), num_boost_round=7)
        s_straight = straight.model_to_string()
        for f in glob.glob(out + "*"):
            os.unlink(f)

        # run A dies mid-write of the iteration-6 snapshot's model file
        # (snapshot 3 = atomic_write hits 1-3; snapshot 6's model = hit 4)
        faultinject.configure("snapshot_kill:4")
        with pytest.raises(InjectedKill):
            lgb.train(dict(p), _ds(), num_boost_round=7)
        faultinject.clear()
        names = os.listdir(tmp_path)
        assert "m.txt.snapshot_iter_3.manifest.json" in names
        assert "m.txt.snapshot_iter_6" not in names   # old state, no hybrid

        # run B auto-resumes from iteration 3 and matches byte-for-byte
        resumed = lgb.train(dict(p, resume=True), _ds(), num_boost_round=7)
        assert resumed.model_to_string() == s_straight

    def test_resume_without_snapshot_trains_from_scratch(self, tmp_path):
        out = str(tmp_path / "m.txt")
        p = dict(BASE, snapshot_freq=3, output_model=out)
        straight = lgb.train(dict(p), _ds(), num_boost_round=5)
        for f in glob.glob(out + "*"):
            os.unlink(f)
        fresh = lgb.train(dict(p, resume=True), _ds(), num_boost_round=5)
        assert fresh.model_to_string() == straight.model_to_string()

    def test_resume_rejects_changed_params(self, tmp_path, caplog):
        out = str(tmp_path / "m.txt")
        p = dict(BASE, snapshot_freq=2, output_model=out)
        lgb.train(dict(p), _ds(), num_boost_round=4)
        with caplog.at_level(logging.WARNING, logger="lightgbm_tpu"):
            bst = lgb.train(dict(p, resume=True, learning_rate=0.05),
                            _ds(), num_boost_round=4)
        assert any("training parameters differ" in r.message
                   for r in caplog.records)
        assert bst.num_trees() == 4        # full retrain, nothing spliced

    def test_resume_accepts_changed_bringup_knobs(self, tmp_path):
        # raising the retry/timeout knobs is the NATURAL response to the
        # crash being resumed from — they never affect the trained model
        # and must not invalidate the snapshot (params_signature excludes
        # them); only the recorded parameters section may differ
        out = str(tmp_path / "m.txt")
        p = dict(BASE, snapshot_freq=2, output_model=out)
        straight = lgb.train(dict(p), _ds(), num_boost_round=4)
        resumed = lgb.train(dict(p, resume=True, dist_init_retries=9,
                                 dist_init_timeout_s=900.0), _ds(),
                            num_boost_round=4)

        def core(s):
            return s[s.index("tree_sizes="):s.index("\nparameters:")]

        assert resumed.num_trees() == 4
        assert core(resumed.model_to_string()) == \
            core(straight.model_to_string())

    def test_resume_rejects_changed_data(self, tmp_path, caplog):
        out = str(tmp_path / "m.txt")
        p = dict(BASE, snapshot_freq=2, output_model=out)
        lgb.train(dict(p), _ds(), num_boost_round=4)
        y2 = Y.copy()
        y2[0] += 1.0
        with caplog.at_level(logging.WARNING, logger="lightgbm_tpu"):
            lgb.train(dict(p, resume=True), lgb.Dataset(X, label=y2),
                      num_boost_round=4)
        assert any("dataset fingerprint differs" in r.message
                   for r in caplog.records)

    def test_interrupted_snapshot_resumes_from_previous(self, tmp_path):
        # a model file with NO manifest (crash between model write and
        # manifest write) must be walked past, not trusted
        out = str(tmp_path / "m.txt")
        p = dict(BASE, snapshot_freq=2, output_model=out)
        straight = lgb.train(dict(p), _ds(), num_boost_round=6)
        s_straight = straight.model_to_string()
        for f in glob.glob(out + "*"):
            os.unlink(f)
        # die on snapshot 4's STATE write (hits: s2=1,2,3; s4 model=4,
        # state=5) -> snapshot_iter_4 model exists, manifest does not
        faultinject.configure("snapshot_kill:5")
        with pytest.raises(InjectedKill):
            lgb.train(dict(p), _ds(), num_boost_round=6)
        faultinject.clear()
        names = os.listdir(tmp_path)
        assert "m.txt.snapshot_iter_4" in names
        assert "m.txt.snapshot_iter_4.manifest.json" not in names
        resumed = lgb.train(dict(p, resume=True), _ds(), num_boost_round=6)
        assert resumed.model_to_string() == s_straight

    def test_snapshot_keep_prunes_old(self, tmp_path):
        out = str(tmp_path / "m.txt")
        lgb.train(dict(BASE, snapshot_freq=1, snapshot_keep=2,
                       output_model=out), _ds(), num_boost_round=5)
        import re
        models = sorted(os.path.basename(m)
                        for m in glob.glob(out + ".snapshot_iter_*")
                        if re.search(r"snapshot_iter_\d+$", m))
        assert models == ["m.txt.snapshot_iter_4", "m.txt.snapshot_iter_5"]
        # sidecars pruned with their models
        assert not os.path.exists(out + ".snapshot_iter_3.manifest.json")
        assert os.path.exists(out + ".snapshot_iter_5.manifest.json")

    def test_save_period_alias(self, tmp_path):
        # satellite: snapshot_freq's reference alias must reach the
        # snapshot machinery end to end
        assert lgb.Config({"save_period": 2}).snapshot_freq == 2
        out = str(tmp_path / "m.txt")
        lgb.train(dict(BASE, save_period=2, output_model=out), _ds(),
                  num_boost_round=4)
        assert os.path.exists(out + ".snapshot_iter_2")
        assert os.path.exists(out + ".snapshot_iter_4.manifest.json")

    def test_trees_and_importances_roundtrip_byte_stable(self):
        # save -> load -> save keeps the tree blocks AND the importance
        # section byte-stable, full and SUBSET saves alike: importances
        # are summed over the written trees at the written %g precision.
        # (feature_infos/parameters legitimately differ on a loaded
        # model — no train_set / raw_params — so compare from the trees
        # through the importance section.)
        def core(s):
            return s[s.index("tree_sizes="):s.index("\nparameters:")]

        bst = lgb.train(dict(BASE), _ds(), num_boost_round=6)
        for kw in ({}, {"num_iteration": 3}, {"start_iteration": 2}):
            s1 = bst.model_to_string(**kw)
            s2 = lgb.Booster(model_str=s1).model_to_string()
            assert core(s1) == core(s2), f"round-trip drift for {kw}"

    def test_resume_not_recorded_in_model_params(self, tmp_path):
        out = str(tmp_path / "m.txt")
        p = dict(BASE, snapshot_freq=2, output_model=out)
        bst = lgb.train(dict(p, resume=True), _ds(), num_boost_round=2)
        assert "[resume:" not in bst.model_to_string()


# ---------------------------------------------------------------------------
# early-stopping NaN poisoning (callback.py satellite)
# ---------------------------------------------------------------------------

class TestEarlyStoppingNonFinite:
    def test_nan_metric_is_not_an_unbeatable_best(self):
        # a custom metric that is NaN for the first 3 iterations, then
        # improves: the old code recorded the first NaN as best_score
        # forever (every later comparison with NaN is False)
        def feval(preds, ds):
            it = len(history)
            history.append(it)
            val = float("nan") if it < 3 else 1.0 / (1.0 + it)
            return ("custom", val, False)

        history = []
        res = {}
        bst = lgb.train(dict(BASE, metric="custom"), _ds(),
                        num_boost_round=10,
                        valid_sets=[_ds()], valid_names=["v"],
                        feval=feval,
                        callbacks=[lgb.early_stopping(3, verbose=False),
                                   lgb.record_evaluation(res)])
        assert bst.best_iteration > 0
        best = bst.best_score["v"]["custom"]
        assert np.isfinite(best)           # NaN never became "best"

    def test_all_nan_metric_stops_cleanly(self):
        def feval(preds, ds):
            return ("custom", float("nan"), False)

        bst = lgb.train(dict(BASE, metric="custom"), _ds(),
                        num_boost_round=10,
                        valid_sets=[_ds()], valid_names=["v"],
                        feval=feval,
                        callbacks=[lgb.early_stopping(2, verbose=False)])
        # stops after the patience window without crashing on the
        # never-recorded best_score_list
        assert bst.best_iteration == 1
