"""The reference's own C API test run against libcapi_train.so.

VERDICT r3 task 5 gate: tests/c_api_test/test_.py from the reference
repository is executed UNMODIFIED against this framework's native
training library, exposed under the reference's file name
(lib_lightgbm.so).  The test exercises the reference-exact ABI surface:
LGBM_DatasetCreateFromFile/Mat/CSR/CSC with typed data + reference
bin-mapper alignment, SetField, SaveBinary + binary reload,
BoosterCreate/AddValidData/UpdateOneIter/GetEval/SaveModel,
CreateFromModelfile, PredictForMat and PredictForFile
(include/LightGBM/c_api.h:109-1237 prototypes).

The reference file is copied from /root/reference at RUN time (it is the
gate fixture, not part of this framework) into a harness tree shaped the
way its find_lib_path() expects.
"""

import os
import shutil
import subprocess
import sys
import sysconfig

import pytest

import lightgbm_tpu as lgb

REF = "/root/reference"
REF_TEST = os.path.join(REF, "tests", "c_api_test", "test_.py")
REF_DATA = os.path.join(REF, "examples", "binary_classification")
SO = os.path.join(os.path.dirname(lgb.__file__), "native",
                  "libcapi_train.so")
SRC = os.path.join(os.path.dirname(lgb.__file__), "native",
                   "capi_train.cpp")


def _ensure_built() -> str:
    if os.path.exists(SO) and os.path.getmtime(SO) >= os.path.getmtime(SRC):
        return ""
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") \
        or sysconfig.get_config_var("VERSION")
    if not inc or not ver:
        return "sysconfig lacks include/version info"
    cmd = (["g++", "-O2", "-shared", "-fPIC", SRC, "-o", SO, f"-I{inc}"]
           + ([f"-L{libdir}"] if libdir else [])
           + [f"-lpython{ver}"]
           + (sysconfig.get_config_var("LIBS") or "").split())
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        return f"build failed: {r.stderr[-400:]}"
    return ""


_BUILD_ERR = _ensure_built()
pytestmark = pytest.mark.skipif(
    bool(_BUILD_ERR) or not os.path.exists(REF_TEST),
    reason=_BUILD_ERR or "reference test file unavailable")


@pytest.fixture(scope="module")
def harness(tmp_path_factory):
    root = tmp_path_factory.mktemp("ref_capi")
    tdir = root / "tests" / "c_api_test"
    tdir.mkdir(parents=True)
    shutil.copy(REF_TEST, tdir / "test_.py")
    exdir = root / "examples" / "binary_classification"
    exdir.mkdir(parents=True)
    for f in ("binary.train", "binary.test"):
        shutil.copy(os.path.join(REF_DATA, f), exdir / f)
    (root / "lib").mkdir()
    os.symlink(SO, root / "lib" / "lib_lightgbm.so")
    return root


def _run(harness, test_name: str) -> subprocess.CompletedProcess:
    env = dict(os.environ,
               LGBM_TPU_FORCE_CPU="1",
               PYTHONPATH=os.path.dirname(os.path.dirname(lgb.__file__)))
    return subprocess.run(
        [sys.executable, "-m", "pytest", "test_.py::" + test_name, "-q",
         "-s", "-p", "no:cacheprovider"],
        cwd=str(harness / "tests" / "c_api_test"), env=env,
        capture_output=True, text=True, timeout=900)


def test_reference_dataset(harness):
    r = _run(harness, "test_dataset")
    assert r.returncode == 0, f"stdout:\n{r.stdout[-3000:]}\n" \
                              f"stderr:\n{r.stderr[-2000:]}"


def test_reference_booster(harness):
    r = _run(harness, "test_booster")
    assert r.returncode == 0, f"stdout:\n{r.stdout[-3000:]}\n" \
                              f"stderr:\n{r.stderr[-2000:]}"
    # the booster loop prints the data_idx=0 eval every 10 iterations —
    # make sure it is a real value, not the untouched 0.0 buffer.  (The
    # reference itself would print 0.0 here: without
    # is_provide_training_metric it returns no data_idx=0 results; this
    # framework reports the training metric, strictly more informative.)
    assert "50 iteration test AUC" in r.stdout
    auc = float(r.stdout.split("50 iteration test AUC")[1].split()[0])
    assert auc > 0.85, f"training AUC {auc} unreasonably low"
