"""Vectorized best-split search over histograms.

Replaces the reference's per-feature sequential threshold scan
``FeatureHistogram::FindBestThresholdSequentially``
(/root/reference/src/treelearner/feature_histogram.hpp:856-1050) and the CUDA
``FindBestSplitsForLeafKernel``
(/root/reference/src/treelearner/cuda/cuda_best_split_finder.cu:603): the
two directional scans (missing->right / missing->left) become cumulative
sums + masked argmax over a ``[2, F, B]`` gain tensor — branchless, all
features at once on the VPU.

Gain / leaf-output math follows feature_histogram.hpp:737-854
(``ThresholdL1``, ``CalculateSplittedLeafOutput``, ``GetSplitGains``) with
lambda_l1 / lambda_l2 / max_delta_step / path_smooth.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

kEpsilon = 1e-15
kMinScore = -jnp.inf


class SplitParams(NamedTuple):
    """Static split hyperparameters (hashable; closed over at jit time)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    path_smooth: float = 0.0


class SplitResult(NamedTuple):
    """Per-leaf best split (SplitInfo analog, split_info.hpp:55)."""
    gain: jax.Array          # f32; <=0 / -inf when invalid
    feature: jax.Array       # int32 (used-feature slot)
    threshold: jax.Array     # int32 bin threshold (go left if bin <= threshold)
    default_left: jax.Array  # bool
    left_sum: jax.Array      # [3] (g, h, count)
    right_sum: jax.Array     # [3]
    left_output: jax.Array   # f32 leaf output
    right_output: jax.Array  # f32


def threshold_l1(s: jax.Array, l1: float) -> jax.Array:
    """ThresholdL1 (feature_histogram.hpp:751)."""
    if l1 <= 0.0:
        return s
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_g, sum_h, p: SplitParams, parent_output=None):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:761-788)."""
    num = -threshold_l1(sum_g, p.lambda_l1)
    denom = sum_h + p.lambda_l2
    if p.path_smooth > 0.0 and parent_output is not None:
        # path smoothing: output = n/(n+λ_smooth) * raw + λ/(n+λ_smooth)*parent
        raw = num / jnp.maximum(denom, kEpsilon)
        # note: reference smooths with data count; approximated by hessian weight
        n_data = sum_h
        smooth_w = n_data / (n_data + p.path_smooth)
        out = raw * smooth_w + parent_output * (1.0 - smooth_w)
    else:
        out = num / jnp.maximum(denom, kEpsilon)
    if p.max_delta_step > 0.0:
        out = jnp.clip(out, -p.max_delta_step, p.max_delta_step)
    return out


def leaf_gain(sum_g, sum_h, p: SplitParams, parent_output=None):
    """GetLeafGain (feature_histogram.hpp:790-820): gain of a leaf with the
    (possibly clipped/smoothed) optimal output."""
    if p.max_delta_step <= 0.0 and p.path_smooth <= 0.0:
        t = threshold_l1(sum_g, p.lambda_l1)
        return t * t / jnp.maximum(sum_h + p.lambda_l2, kEpsilon)
    out = leaf_output(sum_g, sum_h, p, parent_output)
    tg = threshold_l1(sum_g, p.lambda_l1)
    # GetLeafGainGivenOutput: -(2*G̃*w + (H+λ2)*w²)
    return -(2.0 * tg * out + (sum_h + p.lambda_l2) * out * out)


def find_best_split(hist: jax.Array, total: jax.Array, num_bin: jax.Array,
                    na_bin: jax.Array, feature_mask: jax.Array,
                    params: SplitParams, parent_output: jax.Array = None
                    ) -> SplitResult:
    """Best (feature, threshold-bin, missing-direction) for one leaf.

    hist:         [F, B, 3] f32 — per-feature histograms (g, h, count)
    total:        [3] parent aggregates
    num_bin:      [F] int32 valid bin count per feature
    na_bin:       [F] int32 NaN-bin index or -1
    feature_mask: [F] bool — feature_fraction / interaction constraint mask
    """
    f, b, _ = hist.shape
    cum = jnp.cumsum(hist, axis=1)                      # [F, B, 3] inclusive
    bins = jnp.arange(b, dtype=jnp.int32)

    has_na = (na_bin >= 0)
    na_vals = jnp.where(has_na[:, None],
                        jnp.take_along_axis(
                            hist, jnp.maximum(na_bin, 0)[:, None, None]
                            .repeat(3, axis=2), axis=1)[:, 0, :],
                        0.0)                            # [F, 3]

    # dir 0: missing -> right. left(b) = cum[b]  (na bin == last, never left)
    # dir 1: missing -> left.  left(b) = cum[b] + hist[na]
    left0 = cum
    left1 = cum + na_vals[:, None, :]
    lefts = jnp.stack([left0, left1], axis=0)           # [2, F, B, 3]
    rights = total[None, None, None, :] - lefts

    gl, hl, cl = lefts[..., 0], lefts[..., 1], lefts[..., 2]
    gr, hr, cr = rights[..., 0], rights[..., 1], rights[..., 2]

    parent_out = leaf_output(total[0], total[1], params) if parent_output is None \
        else parent_output
    gain_l = leaf_gain(gl, hl, params, parent_out)
    gain_r = leaf_gain(gr, hr, params, parent_out)
    gain_shift = leaf_gain(total[0], total[1], params)
    split_gain = gain_l + gain_r - (gain_shift + params.min_gain_to_split)

    # validity masks (FindBestThresholdSequentially early-continue conditions)
    md = float(params.min_data_in_leaf) - 0.5
    mh = params.min_sum_hessian_in_leaf
    # threshold range: b <= num_bin - 2 excluding the NaN bin from the scan
    max_t = jnp.where(has_na, num_bin - 2, num_bin - 2)  # na bin = num_bin-1
    valid = (bins[None, None, :] <= max_t[None, :, None])
    valid &= feature_mask[None, :, None]
    valid &= (cl >= md) & (cr >= md)
    valid &= (hl >= mh) & (hr >= mh)
    valid &= split_gain > kEpsilon
    # dir-1 scan only exists for features with a NaN bin
    valid &= jnp.stack([jnp.ones((f, b), bool),
                        jnp.broadcast_to(has_na[:, None], (f, b))], axis=0)

    gains = jnp.where(valid, split_gain, kMinScore)     # [2, F, B]
    flat = gains.reshape(-1)
    best = jnp.argmax(flat)                             # first max: dir0, low f, low b
    best_gain = flat[best]
    best_dir = best // (f * b)
    rem = best % (f * b)
    best_f = (rem // b).astype(jnp.int32)
    best_b = (rem % b).astype(jnp.int32)

    sel = lefts[best_dir, best_f, best_b]               # [3]
    left_sum = sel
    right_sum = total - sel
    lo = leaf_output(left_sum[0], left_sum[1], params, parent_out)
    ro = leaf_output(right_sum[0], right_sum[1], params, parent_out)
    return SplitResult(
        gain=best_gain,
        feature=best_f,
        threshold=best_b,
        default_left=(best_dir == 1),
        left_sum=left_sum,
        right_sum=right_sum,
        left_output=lo.astype(jnp.float32),
        right_output=ro.astype(jnp.float32),
    )
