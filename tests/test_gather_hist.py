"""Tiered leaf-gathered histogram construction (grower.py child_hist).

The masked grower builds child histograms from a compacted row gather into
power-of-2 capacity tiers, making per-split work ∝ rows-in-smaller-child —
the reference's smaller-leaf discipline
(/root/reference/src/treelearner/serial_tree_learner.cpp:283-323, CUDA
leaf-indexed construction cuda_histogram_constructor.cu).  Trees must be
IDENTICAL to the masked full-pass build.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.grower import make_grower
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.parallel import make_dp_grower, make_mesh, shard_rows


def _data(n, f=10, b=32, seed=0):
    rng = np.random.RandomState(seed)
    binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    y = (binned[:, 2] >= b // 2).astype(np.float32) \
        + 0.3 * rng.randn(n).astype(np.float32)
    g = (0.5 - y).astype(np.float32)
    vals = np.stack([g, np.ones(n, np.float32), np.ones(n, np.float32)], 1)
    return binned, vals


def _grow(binned, vals, L=15, b=32, **kw):
    f = binned.shape[1]
    grow = make_grower(num_leaves=L, num_bins=b,
                       params=SplitParams(min_data_in_leaf=5), **kw)
    return grow(jnp.asarray(binned), jnp.asarray(vals),
                jnp.ones(f, bool), jnp.full(f, b, jnp.int32),
                jnp.full(f, -1, jnp.int32))


def _assert_same_tree(a, b):
    assert int(a.num_leaves) == int(b.num_leaves) > 2
    np.testing.assert_array_equal(np.asarray(a.split_feature),
                                  np.asarray(b.split_feature))
    np.testing.assert_array_equal(np.asarray(a.threshold_bin),
                                  np.asarray(b.threshold_bin))
    # values differ only by float summation order (gathered vs masked
    # accumulation grouping); structure must be exact, values close
    np.testing.assert_allclose(np.asarray(a.leaf_value),
                               np.asarray(b.leaf_value),
                               rtol=2e-3, atol=5e-5)
    np.testing.assert_array_equal(np.asarray(a.leaf_of_row),
                                  np.asarray(b.leaf_of_row))


class TestGatherTiers:
    def test_tiers_match_full_pass(self):
        # min_gather_rows=512 over 6k rows -> tiers [512,1024,2048,4096] all
        # exercised across the leaf-size distribution
        binned, vals = _data(6000)
        t_full = _grow(binned, vals, gather=False)
        t_tier = _grow(binned, vals, gather=True, min_gather_rows=512)
        _assert_same_tree(t_full, t_tier)

    def test_bagged_rows_gathered(self):
        # zero-weight (out-of-bag) rows still occupy leaves and must be
        # gathered with zero accumulands
        binned, vals = _data(6000, seed=3)
        vals[::3, :] = 0.0
        t_full = _grow(binned, vals, gather=False)
        t_tier = _grow(binned, vals, gather=True, min_gather_rows=512)
        _assert_same_tree(t_full, t_tier)

    @pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
    def test_dp_tiers_match_serial(self):
        # data-parallel (masked full pass) must match the serial grower
        binned, vals = _data(8192)
        b, L = 32, 15
        t_ser = _grow(binned, vals, gather=False)
        mesh = make_mesh((8,), ("data",))
        dp = make_dp_grower(mesh, num_leaves=L, num_bins=b,
                            params=SplitParams(min_data_in_leaf=5))
        f = binned.shape[1]
        t_dp = dp(shard_rows(mesh, binned), shard_rows(mesh, vals),
                  jnp.ones(f, bool), jnp.full(f, b, jnp.int32),
                  jnp.full(f, -1, jnp.int32))
        _assert_same_tree(t_ser, t_dp)
