"""Subprocess worker for the continual-pipeline kill matrix
(tests/test_zcontinual.py): runs N generations of the continual loop
over DETERMINISTIC data and writes the final incumbent's model text.

The driver arms ``LGBM_TPU_FAULTS=<site>:<hit>:exit`` (a real
``os._exit`` — the kill -9 analog) before one invocation, then re-runs
without faults: the restart must SKIP generations whose snapshot
already published (the newest complete snapshot's iteration tells it
how far the dead run got) and converge to a final model BYTE-IDENTICAL
with an uninterrupted run — the publish-is-the-unit-of-redo discipline.

Usage: python continual_worker.py <outdir> <n_chunks>
Writes <outdir>/final.txt (the newest snapshot's model text) and prints
``WORKER_DONE`` on success.
"""

import os
import sys


def chunks_for(seed, n_feat, base_rows, chunk_rows, n_chunks):
    """Deterministic base + chunk series shared by every invocation."""
    import numpy as np
    rs = np.random.RandomState(seed)

    def one(n):
        x = rs.randn(n, n_feat)
        return x, x[:, 0] + 0.5 * x[:, 1] + 0.05 * rs.randn(n)

    base = one(base_rows)
    return base, [one(chunk_rows) for _ in range(n_chunks)]


def main():
    outdir = sys.argv[1]
    n_chunks = int(sys.argv[2])
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from lightgbm_tpu.pipeline.continual import ContinualTrainer
    from lightgbm_tpu.snapshot import find_latest_complete_snapshot

    out_model = os.path.join(outdir, "m.txt")
    params = {"objective": "regression", "num_leaves": 6, "max_bin": 31,
              "min_data_in_leaf": 5, "verbosity": -1,
              "output_model": out_model, "continual_rounds": 2,
              "snapshot_keep": 0}      # keep all: the driver audits them
    rounds = params["continual_rounds"]
    (bx, by), chunks = chunks_for(7, 5, 160, 60, n_chunks)

    trainer = ContinualTrainer(params, bx, by)
    # restart awareness: a generation whose snapshot already published
    # (iteration >= its target) is DONE — the data must still be
    # appended so later generations train on the same rows, but no
    # boosting is redone (byte-identical convergence depends on it)
    found = find_latest_complete_snapshot(out_model)
    done_iter = found[0] if found else 0
    gen_reports = []
    for g in range(n_chunks + 1):
        target = rounds * (g + 1)
        if g > 0:
            x, y = chunks[g - 1]
        if done_iter >= target:
            if g > 0:
                trainer.append_chunk(x, y)
            continue
        rep = trainer.run_generation(*((x, y) if g > 0 else ()))
        gen_reports.append(rep)
        if rep["status"] != "published":
            print(f"WORKER_GEN_FAILED {rep}", flush=True)
            sys.exit(3)
    found = find_latest_complete_snapshot(out_model)
    assert found is not None, "no complete snapshot after the run"
    with open(found[1], encoding="utf-8") as f:
        text = f.read()
    with open(os.path.join(outdir, "final.txt"), "w",
              encoding="utf-8") as f:
        f.write(text)
    print(f"WORKER_DONE iter={found[0]}", flush=True)


if __name__ == "__main__":
    main()
