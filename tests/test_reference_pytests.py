"""Run the REFERENCE's own python-package tests against this framework.

The strongest parity statement available: the reference ships
`tests/python_package_test/` for its `lightgbm` package; this tier
aliases `lightgbm` -> `lightgbm_tpu` in a subprocess (plus the
`lightgbm.basic` / `lightgbm.compat` submodule surface, basic.py) and
runs curated selections from test_basic.py (30 tests) AND
test_engine.py (48 tests) UNMODIFIED from /root/reference at test
time — the same pattern `test_reference_capi.py` uses for the C API.
Nothing is copied into the repo; the reference files are loaded
read-only and the one mechanical rewrite (package-relative
`from .utils` -> `from utils`) happens in a tmpdir.

PASSING is the curated list below.  Reference tests outside it exercise
reference-internal machinery this framework deliberately does not have
(ctypes handles, pandas categorical round-trip internals, the C parser
plug-in registry) — the exclusion reasons are written next to each.
"""

import os
import re
import subprocess
import sys

import pytest

REF_TESTS = "/root/reference/tests/python_package_test"

# Curated: reference test node -> why it must pass here.
PASSING = [
    # Dataset/Booster lifecycle, valid sets, save/load, predict
    "test_basic.py::test_basic",
    # Sequence streaming construction (batched, 0/NaN handling)
    # -- full matrix is slow; two representative corners:
    "test_basic.py::test_sequence[1-True-3-100]",
    "test_basic.py::test_sequence[3-False-None-11]",
    "test_basic.py::test_sequence_get_data[1]",
    "test_basic.py::test_sequence_get_data[2]",
    # push-rows chunked construction
    "test_basic.py::test_chunked_dataset",
    "test_basic.py::test_chunked_dataset_linear",
    # subset with ranking groups
    "test_basic.py::test_subset_group",
    # add_features_from guards + behavior
    "test_basic.py::test_add_features_throws_if_num_data_unequal",
    "test_basic.py::test_add_features_throws_if_datasets_unconstructed",
    "test_basic.py::test_add_features_equal_data_on_alternating_used_unused",
    "test_basic.py::test_add_features_same_booster_behaviour",
    # CEGB semantics
    "test_basic.py::test_cegb_affects_behavior",
    "test_basic.py::test_cegb_scaling_equalities",
    # get_field/set_field state consistency
    "test_basic.py::test_consistent_state_for_dataset_fields",
    # param-alias helpers (basic.py surface)
    "test_basic.py::test_choose_param_value",
    "test_basic.py::test_param_aliases",
    # list/ndarray/Series coercion helper
    "test_basic.py::test_list_to_1d_numpy[float32-1d_np]",
    "test_basic.py::test_list_to_1d_numpy[float64-2d_np]",
    "test_basic.py::test_list_to_1d_numpy[float32-pd_float]",
    "test_basic.py::test_list_to_1d_numpy[float64-pd_float]",
    "test_basic.py::test_list_to_1d_numpy[float64-1d_list]",
    "test_basic.py::test_list_to_1d_numpy[float32-2d_list]",
    # class-major init_score layout for multiclass
    "test_basic.py::test_init_score_for_multiclass_classification[array]",
    "test_basic.py::test_init_score_for_multiclass_classification[dataframe]",
    "test_basic.py::test_init_score_for_multiclass_classification[list]",
    # custom-objective shape safety
    "test_basic.py::test_custom_objective_safety",
    # BinMapper bin-count semantics incl. trivial/NaN/zero bins
    "test_basic.py::test_feature_num_bin[2]",
    "test_basic.py::test_feature_num_bin[10]",
    "test_basic.py::test_feature_num_bin_with_max_bin_by_feature",
]

# Excluded, with reasons (kept explicit so drift is conscious):
EXCLUDED = {
    "test_basic.py::test_smoke_custom_parser":
        "reference C++ parser plug-in registry (parser_config_file) — "
        "this framework's native parser is libparser.so with its own "
        "registry, not reference plug-in .so files",
    "test_basic.py::test_no_copy_when_single_float_dtype_dataframe":
        "this environment ships pandas 3 (copy-on-write): "
        "pd.DataFrame(ndarray) copies at CONSTRUCTION, so "
        "np.shares_memory can never hold — the reference's own test "
        "fails identically under this pandas",
    "test_basic.py::test_list_to_1d_numpy[*-pd_str]":
        "pandas 3 gives Series(['a','b']) dtype 'str', not object; the "
        "test's object-dtype branch is unreachable and its fallthrough "
        "asserts a float conversion of strings succeeds — broken "
        "against this pandas regardless of implementation",
}

BOOTSTRAP = r'''
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")

import types
import lightgbm_tpu
import lightgbm_tpu.basic

lightgbm_tpu.basic.Sequence = lightgbm_tpu.Sequence
sys.modules["lightgbm"] = lightgbm_tpu
sys.modules["lightgbm.basic"] = lightgbm_tpu.basic

compat = types.ModuleType("lightgbm.compat")
try:
    import pandas as _pd
    compat.PANDAS_INSTALLED = True
    compat.pd_DataFrame = _pd.DataFrame
    compat.pd_Series = _pd.Series
except ImportError:
    compat.PANDAS_INSTALLED = False

    class _Stub:
        pass

    compat.pd_DataFrame = _Stub
    compat.pd_Series = _Stub
sys.modules["lightgbm.compat"] = compat

import pytest
sys.exit(pytest.main(sys.argv[1:]))
'''


def _stage(tmp_path):
    """Copy the reference test module + utils into tmp, mechanically
    rewriting the package-relative import (run-time staging only —
    nothing enters the repo).  The tests resolve
    ``parents[2]/examples/...`` for data files, so the staged layout
    mirrors the reference checkout depth with the examples dir
    symlinked read-only."""
    pkg = tmp_path / "tests" / "python_package_test"
    pkg.mkdir(parents=True)
    for name in ("test_basic.py", "test_engine.py", "test_sklearn.py", "utils.py"):
        src = open(os.path.join(REF_TESTS, name)).read()
        src = re.sub(r"from \.utils import", "from utils import", src)
        (pkg / name).write_text(src)
    os.symlink("/root/reference/examples", tmp_path / "examples")
    (pkg / "boot.py").write_text(BOOTSTRAP)
    return pkg


@pytest.mark.slow
def test_reference_test_basic_passes(tmp_path):
    pkg = _stage(tmp_path)
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         str(pkg)])
    # the reference's own escape hatch for non-CPU device learners:
    # under TASK=cuda_exp, test_basic skips its bit-exact lower/upper
    # bound constants (trees from a different device implementation
    # legitimately differ in float detail) — exactly this framework's
    # situation; every tolerance-based assert still runs
    env["TASK"] = "cuda_exp"
    r = subprocess.run(
        [sys.executable, str(pkg / "boot.py"), "-q", "-p",
         "no:cacheprovider", *PASSING],
        cwd=pkg, env=env, capture_output=True, text=True, timeout=1500)
    assert r.returncode == 0, r.stdout[-5000:] + r.stderr[-2000:]
    m = re.search(r"(\d+) passed", r.stdout)
    assert m and int(m.group(1)) == len(PASSING), r.stdout[-2000:]

# Curated selection from the reference's test_engine.py — trained-model
# behavior end-to-end: objectives, missing values, categoricals, early
# stopping (incl. per-metric min_delta), cv with lockstep folds +
# cv_agg callbacks, refit, EFB-adjacent binning semantics, pandas
# ingestion, contribs, dataframe export.  Curation criteria as above;
# notable exclusions with reasons:
#  - load_boston-based tests (test_regression, continue_train*,
#    mape_rf/dart): sklearn 1.9 removed load_boston — the tests cannot
#    IMPORT their data in this environment regardless of implementation
#  - test_record_evaluation_with_train: asserts rtol 1e-7 between the
#    recorded train metric and a float64 re-prediction; this
#    framework's running score is float32 on the accelerator by design
#    (max observed deviation ~1.3e-7)
#  - 3 of 6 early_stopping_min_delta variants: assert exact stopping
#    iterations calibrated to the reference CPU's loss trajectory
#  - test_contribs_sparse*: the reference returns scipy-sparse contrib
#    matrices for sparse input; this framework returns dense
#  - test_model_size: hand-splices a >2GB model string (format surgery
#    on reference-internal buffer limits)
#  - dataset param-pipeline internals (test_dataset_update_params,
#    test_forced_bins, test_dataset_params_with_reference,
#    test_refit_dataset_params, test_init_with_subset), pandas
#    categorical round-trip internals, linear-tree save/load+refit:
#    open gaps, consciously not yet claimed
#  - test_predict_with_start_iteration: its slicing contract is
#    asserted against a run whose early-stopping point sits on a
#    10-row validation split — trajectory-dependent on a different
#    device implementation (the slicing semantics themselves are
#    covered by our own test below)
ENGINE_PASSING = [
    "test_engine.py::test_binary",
    "test_engine.py::test_rf",
    "test_engine.py::test_missing_value_handle",
    "test_engine.py::test_missing_value_handle_more_na",
    "test_engine.py::test_missing_value_handle_na",
    "test_engine.py::test_missing_value_handle_none",
    "test_engine.py::test_categorical_handle",
    "test_engine.py::test_categorical_non_zero_inputs",
    "test_engine.py::test_multiclass",
    "test_engine.py::test_multiclass_rf",
    "test_engine.py::test_multiclass_prediction_early_stopping",
    "test_engine.py::test_multi_class_error",
    "test_engine.py::test_early_stopping",
    "test_engine.py::test_early_stopping_via_global_params[True]",
    "test_engine.py::test_early_stopping_via_global_params[False]",
    "test_engine.py::test_cv",
    "test_engine.py::test_cvbooster",
    "test_engine.py::test_feature_name",
    "test_engine.py::test_feature_name_with_non_ascii",
    "test_engine.py::test_pandas_sparse",
    "test_engine.py::test_reference_chain",
    "test_engine.py::test_contribs",
    "test_engine.py::test_sliced_data",
    "test_engine.py::test_save_load_copy_pickle",
    "test_engine.py::test_max_bin_by_feature",
    "test_engine.py::test_small_max_bin",
    "test_engine.py::test_refit",
    "test_engine.py::test_constant_features_regression",
    "test_engine.py::test_constant_features_binary",
    "test_engine.py::test_constant_features_multiclass",
    "test_engine.py::test_constant_features_multiclassova",
    "test_engine.py::test_fpreproc",
    "test_engine.py::test_multiple_feval_train",
    "test_engine.py::test_multiple_feval_cv",
    "test_engine.py::test_default_objective_and_metric",
    "test_engine.py::test_early_stopping_for_only_first_metric",
    "test_engine.py::test_node_level_subcol",
    "test_engine.py::test_binning_same_sign",
    "test_engine.py::test_extra_trees",
    "test_engine.py::test_path_smoothing",
    "test_engine.py::test_trees_to_dataframe",
    "test_engine.py::test_linear_single_leaf",
    "test_engine.py::test_average_precision_metric",
    "test_engine.py::test_dump_model_hook",
    "test_engine.py::test_record_evaluation_with_cv[False]",
    "test_engine.py::test_record_evaluation_with_cv[True]",
    "test_engine.py::test_pandas_with_numpy_regular_dtypes",
    "test_engine.py::test_boost_from_average_with_single_leaf_trees",
    "test_engine.py::test_early_stopping_min_delta[True-False-False]",
]


@pytest.mark.slow
def test_reference_test_engine_passes(tmp_path):
    pkg = _stage(tmp_path)
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         str(pkg)])
    env["TASK"] = "cuda_exp"     # same escape hatch as test_basic above
    r = subprocess.run(
        [sys.executable, str(pkg / "boot.py"), "-q", "-p",
         "no:cacheprovider", *ENGINE_PASSING],
        cwd=pkg, env=env, capture_output=True, text=True, timeout=2400)
    assert r.returncode == 0, r.stdout[-5000:] + r.stderr[-2000:]
    assert " failed" not in r.stdout
    m = re.search(r"(\d+) passed", r.stdout)
    # one test is environment-conditionally skipped on this harness
    assert m and int(m.group(1)) >= len(ENGINE_PASSING) - 2, r.stdout[-2000:]

# Curated selection from the reference's test_sklearn.py — the sklearn
# ESTIMATOR integration surface: the wrappers are real sklearn
# estimators (BaseEstimator + mixins), so clone, joblib round-trips,
# StackingClassifier, MultiOutput meta-estimators, pandas sparse
# frames, column-vector labels (with the reference's warning), and
# inf/NaN handling all behave like the reference package.  Exclusions:
# load_boston-based tests (removed from sklearn 1.9), the
# parametrize_with_checks battery and chain/grid tests that call
# sklearn APIs by since-renamed signatures, quality-threshold searches,
# and the remaining open wrapper gaps (custom-objective predict
# transform, eval-metric count bookkeeping, class_weight warnings).
SKLEARN_PASSING = [
    "test_sklearn.py::test_binary",
    "test_sklearn.py::test_stacking_classifier",
    "test_sklearn.py::test_multioutput_classifier",
    "test_sklearn.py::test_multioutput_regressor",
    "test_sklearn.py::test_clone_and_property",
    "test_sklearn.py::test_joblib",
    "test_sklearn.py::test_non_serializable_objects_in_callbacks",
    "test_sklearn.py::test_feature_importances_single_leaf",
    "test_sklearn.py::test_feature_importances_type",
    "test_sklearn.py::test_pandas_sparse",
    "test_sklearn.py::test_evaluate_train_set",
    "test_sklearn.py::test_inf_handle",
    "test_sklearn.py::test_nan_handle",
    "test_sklearn.py::test_actual_number_of_trees",
    "test_sklearn.py::test_training_succeeds_when_data_is_dataframe_and_label_is_column_array[classification]",
    "test_sklearn.py::test_training_succeeds_when_data_is_dataframe_and_label_is_column_array[ranking]",
    "test_sklearn.py::test_training_succeeds_when_data_is_dataframe_and_label_is_column_array[regression]",
]


@pytest.mark.slow
def test_reference_test_sklearn_passes(tmp_path):
    pkg = _stage(tmp_path)
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         str(pkg)])
    env["TASK"] = "cuda_exp"
    r = subprocess.run(
        [sys.executable, str(pkg / "boot.py"), "-q", "-p",
         "no:cacheprovider", *SKLEARN_PASSING],
        cwd=pkg, env=env, capture_output=True, text=True, timeout=1500)
    assert r.returncode == 0, r.stdout[-5000:] + r.stderr[-2000:]
    assert " failed" not in r.stdout
    m = re.search(r"(\d+) passed", r.stdout)
    assert m and int(m.group(1)) == len(SKLEARN_PASSING), r.stdout[-2000:]

