"""Metrics registry: labeled counters/gauges/histograms, dict export,
shard-aware aggregation.

Prometheus-shaped but in-process: instruments are created lazily by
(name, sorted label items) and are plain Python objects — incrementing
a counter is one dict lookup + float add, cheap enough for per-iteration
use, and nothing here ever touches the device (device-derived values
must be fetched by the caller, ideally once per snapshot).

``snapshot()`` is deterministic: keys are the canonical
``name{k=v,...}`` strings with labels sorted, values plain
JSON-serializable dicts — so two processes that did the same work
produce byte-identical snapshots (the dp==serial test relies on this).

Multi-process: ``gather_snapshots`` allgathers every process's
snapshot (JSON-encoded through the same fixed-shape u8 transport
``multihost_utils`` needs) and ``aggregate_snapshots`` merges them —
counters sum, gauges keep per-shard values under a ``shard`` label,
histograms merge bucket-wise.  Single-process, both are identity-like.
"""

from __future__ import annotations

import bisect
import json
import threading
from typing import Any, Dict, List, Optional, Tuple

# default histogram buckets: log-ish spacing covering µs..minutes for
# time-valued series and 1..1e9 for count-valued ones
_DEFAULT_BUCKETS = (1e-5, 1e-4, 1e-3, 1e-2, 0.1, 0.25, 1.0, 2.5, 10.0,
                    60.0, 600.0)


def _key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        self.value += v

    def export(self) -> Dict[str, Any]:
        return {"type": "counter", "value": self.value}


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def export(self) -> Dict[str, Any]:
        return {"type": "gauge", "value": self.value}


class Histogram:
    __slots__ = ("buckets", "counts", "count", "sum", "min", "max")

    def __init__(self, buckets: Tuple[float, ...] = _DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, v: float) -> None:
        v = float(v)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def export(self) -> Dict[str, Any]:
        return {"type": "histogram", "count": self.count, "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max if self.count else None,
                "buckets": list(self.buckets), "counts": list(self.counts)}

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0..1) by linear interpolation inside
        the containing bucket, clamped to the observed [min, max] (the
        Prometheus ``histogram_quantile`` estimator).  None when empty.
        Serving latency p50/p99 (serve/server.py /metrics) read this."""
        if self.count <= 0:
            return None
        target = max(0.0, min(1.0, q)) * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target and c > 0:
                lo = self.buckets[i - 1] if i > 0 else self.min
                hi = self.buckets[i] if i < len(self.buckets) else self.max
                lo = max(lo, self.min)
                hi = min(hi, self.max)
                if hi <= lo:
                    return float(hi)
                frac = (target - (cum - c)) / c
                return float(lo + (hi - lo) * frac)
        return float(self.max)


class MetricsRegistry:
    """Lazy instrument registry; thread-safe creation, lock-free use."""

    def __init__(self):
        self._instruments: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, labels: Dict[str, Any], **kw):
        key = _key(name, labels)
        inst = self._instruments.get(key)
        if inst is None:
            with self._lock:
                inst = self._instruments.setdefault(key, cls(**kw))
        if not isinstance(inst, cls):
            raise TypeError(f"metric {key!r} already registered as "
                            f"{type(inst).__name__}, not {cls.__name__}")
        return inst

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str,
                  buckets: Optional[Tuple[float, ...]] = None,
                  **labels: Any) -> Histogram:
        kw = {"buckets": tuple(buckets)} if buckets else {}
        return self._get(Histogram, name, labels, **kw)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Deterministic plain-dict export (sorted keys)."""
        with self._lock:
            items = sorted(self._instruments.items())
        return {k: inst.export() for k, inst in items}

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()


# -- shard-aware aggregation ----------------------------------------------

def aggregate_snapshots(snaps: List[Dict[str, Dict[str, Any]]]
                        ) -> Dict[str, Dict[str, Any]]:
    """Merge per-shard snapshots into one: counters sum, histograms
    merge bucket-wise (bucket layouts must match — they come from the
    same code), gauges that DIFFER across shards are kept per-shard
    under an added ``shard`` label while agreeing gauges collapse.
    Deterministic: output keys sorted, merge order is the list order."""
    if len(snaps) == 1:
        return dict(sorted(snaps[0].items()))
    out: Dict[str, Dict[str, Any]] = {}
    gauge_seen: Dict[str, List[Tuple[int, float]]] = {}
    for si, snap in enumerate(snaps):
        for key, rec in snap.items():
            t = rec.get("type")
            if t == "gauge":
                gauge_seen.setdefault(key, []).append(
                    (si, rec.get("value", 0.0)))
                continue
            cur = out.get(key)
            if cur is None:
                out[key] = json.loads(json.dumps(rec))   # deep copy
            elif t == "counter":
                cur["value"] += rec["value"]
            elif t == "histogram":
                cur["count"] += rec["count"]
                cur["sum"] += rec["sum"]
                for mi, (a, b) in enumerate(zip(cur["counts"],
                                                rec["counts"])):
                    cur["counts"][mi] = a + b
                for f, pick in (("min", min), ("max", max)):
                    vals = [v for v in (cur[f], rec[f]) if v is not None]
                    cur[f] = pick(vals) if vals else None
    for key, vals in gauge_seen.items():
        if len({v for _, v in vals}) == 1:
            out[key] = {"type": "gauge", "value": vals[0][1]}
        else:
            base, brace, rest = key.partition("{")
            for si, v in vals:
                inner = f"shard={si}" + ("," + rest[:-1] if brace else "")
                out[f"{base}{{{inner}}}"] = {"type": "gauge", "value": v}
    return dict(sorted(out.items()))


_PROM_NAME_RE = None


def _prom_name(name: str) -> str:
    global _PROM_NAME_RE
    if _PROM_NAME_RE is None:
        import re
        _PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
    out = _PROM_NAME_RE.sub("_", name)
    return "_" + out if out and out[0].isdigit() else out


def _prom_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    esc = {k: str(v).replace("\\", "\\\\").replace('"', '\\"')
           for k, v in labels.items()}
    return "{" + ",".join(f'{_prom_name(k)}="{esc[k]}"'
                          for k in sorted(esc)) + "}"


def _parse_key(key: str):
    """``name{a=b,c=d}`` snapshot key -> (name, labels dict)."""
    base, brace, rest = key.partition("{")
    labels: Dict[str, str] = {}
    if brace:
        for part in rest[:-1].split(","):
            k, _, v = part.partition("=")
            if k:
                labels[k] = v
    return base, labels


def prometheus_text(snap: Dict[str, Any]) -> str:
    """Render a metrics snapshot in the Prometheus text exposition
    format (v0.0.4) — the ``?format=prom`` answer of the serve
    ``/metrics`` endpoint.

    Typed instruments map directly (histograms emit cumulative
    ``_bucket``/``_sum``/``_count`` series with ``le`` labels); plain
    numeric entries (``compile.*``, ``perf.*``) become gauges; string
    entries (the roofline ``bound`` verdicts) become info-style
    ``name{value="..."} 1`` gauges; nested plain dicts
    (``serve.engine``, ``serve.latency_quantiles``,
    ``compile.traces`` by-name) flatten one level, numeric leaves
    only.  Deterministic: keys sorted, one ``# TYPE`` line per
    metric family."""
    lines: List[str] = []
    typed: Dict[str, str] = {}

    def emit(name: str, typ: str, labels: Dict[str, str],
             value: float) -> None:
        pname = _prom_name(name)
        if pname not in typed:
            typed[pname] = typ
            lines.append(f"# TYPE {pname} {typ}")
        lines.append(f"{pname}{_prom_labels(labels)} {value!r}")

    for key in sorted(snap):
        rec = snap[key]
        name, labels = _parse_key(key)
        if isinstance(rec, bool):
            emit(name, "gauge", labels, float(rec))
        elif isinstance(rec, (int, float)):
            emit(name, "gauge", labels, float(rec))
        elif isinstance(rec, str):
            emit(name, "gauge", dict(labels, value=rec), 1.0)
        elif isinstance(rec, dict) and rec.get("type") == "counter":
            emit(name, "counter", labels, float(rec.get("value", 0.0)))
        elif isinstance(rec, dict) and rec.get("type") == "gauge":
            emit(name, "gauge", labels, float(rec.get("value", 0.0)))
        elif isinstance(rec, dict) and rec.get("type") == "histogram":
            pname = _prom_name(name)
            if pname not in typed:
                typed[pname] = "histogram"
                lines.append(f"# TYPE {pname} histogram")
            cum = 0
            for b, c in zip(list(rec.get("buckets", [])) + ["+Inf"],
                            rec.get("counts", [])):
                cum += c
                lines.append(f"{pname}_bucket"
                             f"{_prom_labels(dict(labels, le=str(b)))}"
                             f" {cum}")
            lines.append(f"{pname}_sum{_prom_labels(labels)} "
                         f"{float(rec.get('sum', 0.0))!r}")
            lines.append(f"{pname}_count{_prom_labels(labels)} "
                         f"{int(rec.get('count', 0))}")
        elif isinstance(rec, dict):
            for sub in sorted(rec):
                v = rec[sub]
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                emit(f"{name}.{sub}", "gauge", labels, float(v))
    return "\n".join(lines) + "\n"


def gather_snapshots(snap: Dict[str, Dict[str, Any]]
                     ) -> List[Dict[str, Dict[str, Any]]]:
    """All processes' snapshots, in process order (multi-process pods;
    identity wrapper for a single process).  JSON rides a fixed-shape
    u8 array: ``process_allgather`` needs congruent shapes, so every
    process pads its encoding to the allreduced max length."""
    import jax
    if jax.process_count() <= 1:
        return [snap]
    import numpy as np
    from jax.experimental import multihost_utils
    raw = json.dumps(snap).encode()
    n = np.asarray(len(raw))
    nmax = int(np.max(multihost_utils.process_allgather(n)))
    buf = np.zeros(nmax + 8, np.uint8)
    buf[:8] = np.frombuffer(np.asarray([len(raw)], np.int64).tobytes(),
                            np.uint8)
    buf[8:8 + len(raw)] = np.frombuffer(raw, np.uint8)
    allbuf = np.asarray(multihost_utils.process_allgather(buf))
    out = []
    for row in allbuf:
        ln = int(np.frombuffer(row[:8].tobytes(), np.int64)[0])
        out.append(json.loads(row[8:8 + ln].tobytes().decode()))
    return out
