"""Measure fused-chunk training throughput on the real TPU.

Run: python tools/bench_fused.py [n_rows] [num_leaves] [chunk] [split_batch]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    num_leaves = int(sys.argv[2]) if len(sys.argv) > 2 else 31
    chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 25
    split_batch = int(sys.argv[4]) if len(sys.argv) > 4 else 1

    rng = np.random.RandomState(0)
    f = 28
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
             + 0.4 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0).astype(np.float32)

    import jax
    print(f"devices={jax.devices()}", file=sys.stderr, flush=True)
    import lightgbm_tpu as lgb

    params = {"objective": "binary", "num_leaves": num_leaves,
              "learning_rate": 0.1, "max_bin": 63, "min_data_in_leaf": 20,
              "verbosity": 0, "fused_chunk": chunk,
              "split_batch": split_batch}
    t0 = time.time()
    ds = lgb.Dataset(x, label=y, params=params)   # bin at the CLAIMED max_bin
    ds.construct()
    print(f"bin: {time.time()-t0:.1f}s", file=sys.stderr, flush=True)

    bst = lgb.Booster(params=params, train_set=ds)
    m = bst._model
    assert m.supports_fused(), "fused path not eligible?!"

    t0 = time.time()
    m.train_chunk(chunk)                 # compile + first chunk
    print(f"compile+chunk1({chunk} iters): {time.time()-t0:.1f}s",
          file=sys.stderr, flush=True)

    t0 = time.time()
    nchunks = 3
    for _ in range(nchunks):
        m.train_chunk(chunk)
    dt = time.time() - t0
    ips = nchunks * chunk / dt
    print(f"steady: {dt:.1f}s for {nchunks * chunk} iters -> "
          f"{ips:.2f} iters/s ({1000/ips:.0f} ms/iter)  "
          f"vs_baseline(3.843)={ips/3.843:.2f}", file=sys.stderr, flush=True)

    from lightgbm_tpu.metrics import _auc
    auc = _auc(y, np.asarray(m.train_score())[:, 0], None)
    print(f"train-AUC after {m.iter_} iters: {auc:.4f}", file=sys.stderr)


if __name__ == "__main__":
    main()
