"""Versioned model registry with atomic hot swap.

Serving must outlive any single model file: the registry holds
(version -> :class:`ServedModel`) where each entry pairs a loaded
``Booster`` with its compiled :class:`~.engine.PredictorEngine`, and an
atomic "current" pointer.  ``activate`` swaps the pointer under a lock
— a reader that already resolved :meth:`current` keeps its handle, so
in-flight requests finish on the version they started on while new
requests pick up the swap (the hot-reload contract, docs/Serving.md).

Models load from model files / strings / live Boosters, or from
``snapshot.py`` training snapshots: :meth:`load_snapshot` picks the
newest snapshot of an ``output_model`` whose manifest is present and
parseable (the manifest-written-last marker of a COMPLETE snapshot) —
serving has no training dataset, so the params-signature and
data-fingerprint checks that gate training auto-resume do not apply.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class NoModelError(RuntimeError):
    """The registry has no active model."""


class ServedModel:
    """One immutable (version, booster, engine) serving unit."""

    __slots__ = ("version", "booster", "engine", "source", "loaded_at")

    def __init__(self, version: str, booster, engine, source: str):
        self.version = version
        self.booster = booster
        self.engine = engine
        self.source = source
        self.loaded_at = time.time()

    def describe(self) -> dict:
        return {"version": self.version, "source": self.source,
                "loaded_at": self.loaded_at,
                "num_trees": len(self.booster.trees),
                "num_class": self.booster._num_tree_per_iteration,
                "num_features": self.booster.num_feature(),
                "fingerprint": self.engine.fingerprint
                if self.engine is not None else None}


class ModelRegistry:
    def __init__(self, *, max_batch: Optional[int] = None,
                 min_bucket: int = 16, build_engine: bool = True):
        self._models: Dict[str, ServedModel] = {}
        self._current: Optional[ServedModel] = None
        self._lock = threading.Lock()
        self._next_version = 1
        self._engine_opts = {"max_batch": max_batch,
                             "min_bucket": min_bucket}
        self._build_engine = build_engine

    # -- loading -----------------------------------------------------------
    def load(self, model_file: Optional[str] = None,
             model_str: Optional[str] = None, booster=None,
             version: Optional[str] = None, source: str = "",
             activate: bool = True) -> str:
        """Load one model (exactly one of file / string / booster),
        register it, and (by default) atomically make it current."""
        from ..booster import Booster
        if sum(a is not None
               for a in (model_file, model_str, booster)) != 1:
            raise ValueError("load needs exactly one of model_file, "
                             "model_str, booster")
        if booster is None:
            booster = Booster(model_file=model_file, model_str=model_str)
            source = source or (model_file or "<model_str>")
        else:
            source = source or "<booster>"
        engine = None
        if self._build_engine:
            from ..utils.log import Log
            from .engine import EngineUnsupported, PredictorEngine
            try:
                engine = PredictorEngine.from_booster(booster,
                                                      **self._engine_opts)
            except EngineUnsupported as e:
                # an engine-unsupported model is still SERVABLE — the
                # batch path falls back to the host walk exactly like
                # Booster.predict does; only the bucketed cache is lost
                Log.warning(f"serve: bucketed engine unavailable for "
                            f"{source} ({e}); serving via host walk")
                booster._engine_cache = False
            else:
                # make this THE booster's predictor too: Booster.predict
                # on the serve path then rides the same bucketed cache,
                # and the engine's compile ledger (surfaced via
                # /metrics) sees every batch
                booster._engine_cache = engine
        with self._lock:
            if version is None:
                version = f"v{self._next_version}"
            self._next_version += 1
            if version in self._models:
                raise ValueError(f"model version {version!r} already "
                                 "registered")
            served = ServedModel(version, booster, engine, source)
            self._models[version] = served
            if activate or self._current is None:
                self._current = served
        return version

    def load_snapshot(self, output_model: str,
                      version: Optional[str] = None,
                      activate: bool = True) -> str:
        """Load the newest COMPLETE snapshot of ``output_model``
        (manifest present + parseable, snapshot.py)."""
        from ..snapshot import find_latest_complete_snapshot
        found = find_latest_complete_snapshot(output_model)
        if found is None:
            raise FileNotFoundError(
                f"no complete snapshot of {output_model!r} found")
        it, path = found
        return self.load(model_file=path, version=version,
                         source=f"{path} (snapshot iter {it})",
                         activate=activate)

    # -- swap / lookup -----------------------------------------------------
    def activate(self, version: str) -> None:
        """Atomically point new requests at ``version``; handles already
        resolved via :meth:`current` are unaffected."""
        with self._lock:
            if version not in self._models:
                raise KeyError(f"unknown model version {version!r}")
            self._current = self._models[version]

    def current(self) -> ServedModel:
        with self._lock:
            if self._current is None:
                raise NoModelError("no model loaded")
            return self._current

    def get(self, version: Optional[str] = None) -> ServedModel:
        if version is None:
            return self.current()
        with self._lock:
            try:
                return self._models[version]
            except KeyError:
                raise KeyError(f"unknown model version {version!r}") \
                    from None

    def unload(self, version: str) -> None:
        """Drop a non-current version (the current one must be swapped
        away first)."""
        with self._lock:
            if self._current is not None \
                    and self._current.version == version:
                raise ValueError("cannot unload the current version; "
                                 "activate another first")
            self._models.pop(version, None)

    def versions(self) -> List[dict]:
        with self._lock:
            cur = self._current.version if self._current else None
            return [dict(m.describe(), current=(v == cur))
                    for v, m in sorted(self._models.items())]
