"""Compiled predictor engine: SoA ensemble + bucketed compile cache.

The training loop already owns a fast binned traversal
(``predict_device.traverse_tree_binned``), but nothing exposed it to
callers at serving time — ``Booster.predict`` walked host trees row
group by row group.  This module flattens a trained ensemble ONCE into
stacked structure-of-arrays device tensors (the SoA layout
arXiv:2011.02022 and arXiv:1706.08359 identify as where GBDT inference
throughput lives) and runs the whole-forest traversal
(``predict_device.traverse_forest_binned``) under a compile cache keyed
by (model fingerprint, padded batch bucket):

- **Model-derived binning.**  Each feature's bin table is the sorted
  set of split thresholds the ENSEMBLE actually uses (not the training
  ``BinMapper`` — a loaded model file has no mappers).  With
  ``bin(x) = searchsorted(T_f, x, side="left")`` the reference decision
  ``x <= threshold`` is EXACTLY ``bin(x) <= index(threshold)``, so
  traversal over bins reproduces ``tree_model.Tree.predict_leaf``
  bit-for-bit.  Binning runs host-side in float64 — the one stage that
  cannot run in f32 without breaking bit-exact parity (a raw value that
  ties a threshold after f32 rounding may cross it); the opt-in
  ``serve_device_binning`` mode moves it on-device in f32 for
  throughput at the cost of exactness on such ties.
- **Bucketed batches.**  Row counts round up to power-of-two buckets
  (floored at ``min_bucket``, capped at ``max_batch`` when set), so the
  number of distinct traversal shapes — and therefore XLA compiles —
  is bounded by ~log2(max_batch) per model, measured by
  ``predict_device.forest_trace_count`` and surfaced via
  ``compile_stats()`` / ``utils/compile_cache.watch_compiles``.
- **Exact scores.**  The device returns leaf ids; leaf values are
  accumulated HOST-side in float64 in tree order — the same float ops,
  in the same order, as ``Booster.predict``, so engine scores (and the
  serve path built on them) are byte-identical to the reference
  predictor, linear trees and DART/RF tree weights included.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.shapes import bucket_rows, round_up_pow2

_CAT_BIT = 1
_DEFAULT_LEFT_BIT = 2
_MISSING_SHIFT = 2
_ALWAYS_LEFT = np.int32(1 << 30)   # stump sentinel threshold: rank <= this


class EngineUnsupported(ValueError):
    """Model shape the SoA engine cannot represent (callers fall back to
    the host-tree path)."""


class _FeatureTable:
    """Per-feature model-derived bin table."""

    __slots__ = ("kind", "thresholds", "cats", "miss_nan", "na_bin",
                 "num_bins")

    def __init__(self, kind: str):
        self.kind = kind                    # "num" | "cat" | "unused"
        self.thresholds = np.empty(0, np.float64)
        self.cats = np.empty(0, np.int64)
        self.miss_nan = False               # any node routes NaN by flag
        self.na_bin = -1
        self.num_bins = 1


def _feature_tables(trees, num_features: int) -> List[_FeatureTable]:
    tables = [_FeatureTable("unused") for _ in range(num_features)]
    thr_acc: Dict[int, List[np.ndarray]] = {}
    cat_acc: Dict[int, set] = {}
    miss_acc: Dict[int, set] = {}
    for t in trees:
        n = t.num_nodes()
        if n == 0:
            continue
        sf = t.split_feature[:n]
        dt = t.decision_type[:n]
        is_cat = (dt & _CAT_BIT) != 0
        miss = (dt >> _MISSING_SHIFT) & 3
        for f in np.unique(sf[~is_cat]):
            m = (sf == f) & ~is_cat
            thr_acc.setdefault(int(f), []).append(t.threshold[:n][m])
            # miss kind 2 (NaN) routes NaN by the node's default_left
            # flag; kinds 0/1 convert NaN to 0.0 first
            # (tree_model._decide) — record which behaviors appear
            miss_acc.setdefault(int(f), set()).update(
                {2} if (miss[m] == 2).any() else set())
            miss_acc[int(f)].update(
                {0} if (miss[m] != 2).any() else set())
        for i in np.nonzero(is_cat)[0]:
            f = int(sf[i])
            ci = int(t.threshold[i])
            lo, hi = t.cat_boundaries[ci], t.cat_boundaries[ci + 1]
            words = t.cat_threshold[lo:hi]
            cset = cat_acc.setdefault(f, set())
            for wi, w in enumerate(words):
                w = int(w)
                while w:
                    b = w & -w
                    cset.add(32 * wi + b.bit_length() - 1)
                    w ^= b
    for f, chunks in thr_acc.items():
        if f in cat_acc:
            raise EngineUnsupported(
                f"feature {f} has both numerical and categorical splits")
        if len(miss_acc[f]) > 1:
            # a trained model never mixes NaN-routing and NaN-converting
            # nodes on one feature (they come from one BinMapper); a
            # hand-merged model could — refuse rather than mispredict
            raise EngineUnsupported(
                f"feature {f} mixes NaN-routing and NaN-converting "
                "split nodes")
        tab = tables[f]
        tab.kind = "num"
        tab.miss_nan = miss_acc[f] == {2}
        tab.thresholds = np.unique(np.concatenate(chunks))
        # bins 0..len(T) from searchsorted, +1 reserved NaN bin when the
        # feature routes NaN by flag
        tab.na_bin = len(tab.thresholds) + 1 if tab.miss_nan else -1
        tab.num_bins = len(tab.thresholds) + (2 if tab.miss_nan else 1)
    for f, cset in cat_acc.items():
        tab = tables[f]
        tab.kind = "cat"
        tab.cats = np.asarray(sorted(cset), np.int64)
        tab.num_bins = len(tab.cats) + 1        # + unseen/NaN sentinel
    return tables


# one shared jitted traversal for ALL engines: two engines whose SoA
# shapes match (common in tests and A/B model versions) reuse the same
# compile-cache entries — the model arrays travel as call arguments, so
# the cache key is (shapes, steps), never the model content
_shared_traverse = None


def _traverse_jit():
    global _shared_traverse
    if _shared_traverse is None:
        import jax
        from ..predict_device import traverse_forest_binned
        _shared_traverse = jax.jit(traverse_forest_binned,
                                   static_argnames=("steps",))
    return _shared_traverse


class PredictorEngine:
    """One trained ensemble, flattened for batched device traversal.

    Thread-safe: ``leaf_ids``/``raw_scores``/``predict`` may be called
    concurrently (the jit cache and host accumulation are functional;
    the bucket ledger is lock-guarded).
    """

    def __init__(self, trees, tree_weights, num_class: int,
                 num_features: int, objective=None,
                 average_output: bool = False, *,
                 max_batch: Optional[int] = None, min_bucket: int = 16,
                 fingerprint: Optional[str] = None):
        import jax.numpy as jnp

        self.trees = list(trees)
        self.tree_weights = list(tree_weights)
        self.num_class = max(1, int(num_class))
        self.num_features = int(num_features)
        self.objective = objective
        self.average_output = bool(average_output)
        self.max_batch = int(max_batch) if max_batch else None
        self.min_bucket = max(1, int(min_bucket))
        if self.max_batch is not None:
            self.min_bucket = min(self.min_bucket, self.max_batch)
        if self.num_features < 1:
            raise EngineUnsupported("model has no features")

        self.tables = _feature_tables(self.trees, self.num_features)
        self._build_soa()
        self.fingerprint = fingerprint or self._fingerprint()
        self._lock = threading.Lock()
        self._buckets_seen: Dict[int, int] = {}

        d = self._dev = {}
        for name in ("split_feature", "threshold_bin", "left_child",
                     "right_child", "cat_index"):
            d[name] = jnp.asarray(getattr(self, "_" + name), jnp.int32)
        d["default_left"] = jnp.asarray(self._default_left, jnp.bool_)
        d["is_cat_node"] = jnp.asarray(self._is_cat_node, jnp.bool_)
        d["cat_table"] = jnp.asarray(self._cat_table, jnp.int32)
        d["na_bin"] = jnp.asarray(self._na_bin, jnp.int32)
        self._bin_dev = None               # lazy device-binning tables

    def _traverse(self, binned):
        d = self._dev
        return _traverse_jit()(
            binned, d["split_feature"], d["threshold_bin"],
            d["default_left"], d["left_child"], d["right_child"],
            d["na_bin"], d["is_cat_node"], d["cat_index"],
            d["cat_table"], steps=self._steps)

    # -- construction ------------------------------------------------------
    def _build_soa(self) -> None:
        trees = self.trees
        T = len(trees)
        M = max([t.num_nodes() for t in trees] + [1])
        L = max([t.num_leaves for t in trees] + [1])
        self._split_feature = np.zeros((T, M), np.int32)
        self._threshold_bin = np.zeros((T, M), np.int32)
        self._default_left = np.zeros((T, M), bool)
        self._left_child = np.full((T, M), -1, np.int32)
        self._right_child = np.full((T, M), -1, np.int32)
        self._is_cat_node = np.zeros((T, M), bool)
        self._cat_index = np.zeros((T, M), np.int32)
        self.leaf_values = np.zeros((T, L), np.float64)
        self._na_bin = np.asarray([tab.na_bin for tab in self.tables],
                                  np.int32)
        cat_rows: List[np.ndarray] = []
        max_cat_bins = max([tab.num_bins for tab in self.tables
                            if tab.kind == "cat"] + [1])
        depth = 1
        for ti, t in enumerate(trees):
            n = t.num_nodes()
            self.leaf_values[ti, :t.num_leaves] = t.leaf_value[:t.num_leaves]
            if t.num_leaves <= 1:
                # stump: the padded root routes every row (NaN included)
                # to leaf 0
                self._threshold_bin[ti, 0] = _ALWAYS_LEFT
                self._default_left[ti, 0] = True
                continue
            depth = max(depth, t.max_depth())
            sf = t.split_feature[:n]
            dt = t.decision_type[:n]
            is_cat = (dt & _CAT_BIT) != 0
            self._split_feature[ti, :n] = sf
            self._default_left[ti, :n] = (dt & _DEFAULT_LEFT_BIT) != 0
            self._left_child[ti, :n] = t.left_child[:n]
            self._right_child[ti, :n] = t.right_child[:n]
            self._is_cat_node[ti, :n] = is_cat
            for f in np.unique(sf[~is_cat]):
                tab = self.tables[int(f)]
                m = (sf == f) & ~is_cat
                self._threshold_bin[ti, :n][m] = np.searchsorted(
                    tab.thresholds, t.threshold[:n][m], side="left")
            for i in np.nonzero(is_cat)[0]:
                tab = self.tables[int(sf[i])]
                # rank row over the feature's model-wide category table:
                # 0 = in this node's left set, 1 = not (sentinel bin —
                # unseen / negative / NaN — is always 1 -> right, the
                # _cat_contains fall-through)
                row = np.ones(max_cat_bins, np.int32)
                if len(tab.cats):
                    contained = t._cat_contains(
                        int(t.threshold[i]), tab.cats.astype(np.float64))
                    row[:len(tab.cats)] = np.where(contained, 0, 1)
                self._cat_index[ti, i] = len(cat_rows)
                cat_rows.append(row)
                # threshold_bin stays 0: go left iff rank <= 0
        self._cat_table = (np.stack(cat_rows) if cat_rows
                           else np.zeros((1, 1), np.int32))
        self._steps = round_up_pow2(depth)

    def _fingerprint(self) -> str:
        h = hashlib.sha256()
        h.update(f"{len(self.trees)}:{self.num_class}:"
                 f"{self.num_features}".encode())
        for arr in (self._split_feature, self._threshold_bin,
                    self._left_child, self.leaf_values,
                    np.asarray(self.tree_weights, np.float64)):
            h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()[:16]

    # -- binning -----------------------------------------------------------
    def bin_rows(self, x: np.ndarray) -> np.ndarray:
        """Exact host-side (f64) model-derived binning: [n, F] float ->
        [n, F] int32 in each feature's own bin space."""
        x = np.asarray(x, np.float64)
        out = np.zeros(x.shape, np.int32)
        for f, tab in enumerate(self.tables):
            if tab.kind == "num":
                v = x[:, f]
                isnan = np.isnan(v)
                if tab.miss_nan:
                    out[:, f] = np.where(
                        isnan, tab.na_bin,
                        np.searchsorted(tab.thresholds,
                                        np.where(isnan, 0.0, v), "left"))
                else:
                    out[:, f] = np.searchsorted(
                        tab.thresholds, np.where(isnan, 0.0, v), "left")
            elif tab.kind == "cat" and len(tab.cats):
                v = x[:, f]
                # trunc-toward-zero + NaN/inf -> -1, exactly
                # tree_model._decide's CategoricalDecision input mapping
                iv = np.where(np.isfinite(v), v, -1.0).astype(np.int64)
                pos = np.searchsorted(tab.cats, iv)
                pos = np.clip(pos, 0, len(tab.cats) - 1)
                out[:, f] = np.where(tab.cats[pos] == iv, pos,
                                     len(tab.cats))
        return out

    def _bucket(self, n: int) -> int:
        # the ONE shared bucketing policy (utils/shapes.py) — the same
        # pow2-with-floor rule now also buckets validation-set rows and
        # (via bucket_leaves) the grower's leaf budget
        return bucket_rows(n, min_bucket=self.min_bucket,
                           cap=self.max_batch)

    def _device_bin_tables(self):
        import jax.numpy as jnp
        if self._bin_dev is None:
            B = max([len(t.thresholds) for t in self.tables] + [1])
            thr = np.full((self.num_features, B), np.inf, np.float32)
            zero_bin = np.zeros(self.num_features, np.int32)
            for f, tab in enumerate(self.tables):
                if tab.kind == "num":
                    thr[f, :len(tab.thresholds)] = tab.thresholds
                    zero_bin[f] = np.searchsorted(tab.thresholds, 0.0,
                                                  "left")
                elif tab.kind == "cat":
                    raise EngineUnsupported(
                        "device binning supports numerical features only")
            self._bin_dev = (jnp.asarray(thr), jnp.asarray(zero_bin))
        return self._bin_dev

    # -- traversal ---------------------------------------------------------
    def leaf_ids(self, x: np.ndarray,
                 device_binning: bool = False) -> np.ndarray:
        """Leaf index per (row, tree): [n, F] raw floats -> [n, T] int32.
        Batches above the bucket cap are processed in max-bucket chunks;
        zero rows never touch the device."""
        import jax
        x = np.asarray(x, np.float64)
        n = len(x)
        T = len(self.trees)
        if n == 0 or T == 0:
            return np.zeros((n, T), np.int32)
        cap = self._bucket(n)
        chunks = []
        for lo in range(0, n, cap):
            sub = x[lo:lo + cap]
            bucket = self._bucket(len(sub))
            with self._lock:
                self._buckets_seen[bucket] = \
                    self._buckets_seen.get(bucket, 0) + 1
            if device_binning:
                thr, zero_bin = self._device_bin_tables()
                from ..predict_device import bin_rows_device
                xpad = np.zeros((bucket, self.num_features), np.float32)
                xpad[:len(sub)] = sub
                binned = bin_rows_device(jax.numpy.asarray(xpad), thr,
                                         self._dev["na_bin"], zero_bin)
            else:
                pad = np.zeros((bucket, self.num_features), np.int32)
                pad[:len(sub)] = self.bin_rows(sub)
                binned = jax.numpy.asarray(pad)
            # the serve hot path's ONE device fetch: leaf ids are the
            # data the host accumulation genuinely needs
            out = jax.device_get(self._traverse(binned))
            chunks.append(np.asarray(out[:len(sub)], np.int32))
        return np.concatenate(chunks, axis=0)

    # -- scoring -----------------------------------------------------------
    def raw_scores(self, x: np.ndarray, t0: int = 0,
                   t1: Optional[int] = None,
                   leaves: Optional[np.ndarray] = None,
                   device_binning: bool = False) -> np.ndarray:
        """[n, num_class] float64 raw scores over trees [t0, t1) —
        float-op-for-float-op identical to ``Booster.predict``'s host
        accumulation (tree order, f64, tree_weights applied)."""
        x = np.asarray(x, np.float64)
        t1 = len(self.trees) if t1 is None else t1
        k = self.num_class
        if leaves is None:
            leaves = self.leaf_ids(x, device_binning=device_binning)
        score = np.zeros((len(x), k))
        for ti in range(t0, t1):
            t = self.trees[ti]
            w = self.tree_weights[ti] if ti < len(self.tree_weights) else 1.0
            lv = leaves[:, ti]
            vals = t.linear_leaf_outputs(lv, x) if t.is_linear \
                else t.leaf_value[lv]
            score[:, ti % k] += w * vals
        return score

    def predict(self, x, raw_score: bool = False,
                device_binning: bool = False) -> np.ndarray:
        """Full-model prediction with the ``Booster.predict`` output
        contract (averaging for RF, objective output conversion — the
        shared ``booster._finalize_score`` tail)."""
        from ..booster import _finalize_score
        x = np.asarray(x, np.float64)
        k = self.num_class
        n, t1 = len(x), len(self.trees)
        if n == 0:
            out_f32 = not raw_score and self.objective is not None
            shape = (0, k) if k > 1 else (0,)
            return np.zeros(shape, np.float32 if out_f32 else np.float64)
        score = self.raw_scores(x, device_binning=device_binning)
        return _finalize_score(score, k, self.objective,
                               self.average_output, 0, t1, raw_score)

    # -- verification ------------------------------------------------------
    def _probe_candidates(self) -> List[np.ndarray]:
        """Per-feature probe values aimed at the engine's risk surface:
        the model's own split thresholds (exact tie inputs — the values
        f32 rounding would misroute), midpoints between consecutive
        thresholds, out-of-range values, NaN, and every categorical's
        in/out-of-set and unseen values."""
        cands: List[np.ndarray] = []
        for tab in self.tables:
            if tab.kind == "num" and len(tab.thresholds):
                t = tab.thresholds
                mids = (t[:-1] + t[1:]) / 2.0 if len(t) > 1 \
                    else np.empty(0)
                c = np.concatenate([t, mids, [t[0] - 1.0, t[-1] + 1.0,
                                              0.0, np.nan]])
            elif tab.kind == "cat" and len(tab.cats):
                c = np.concatenate([tab.cats.astype(np.float64),
                                    [tab.cats[-1] + 1.0, -1.0, np.nan]])
            else:
                c = np.zeros(1)
            cands.append(c)
        return cands

    def _f32_consensus_mask(self, x: np.ndarray) -> np.ndarray:
        """Rows whose f32 on-device binning provably agrees with the
        exact f64 binning — only those can be byte-compared against the
        host walk (``serve_device_binning`` documents tie inexactness
        as the mode's accepted cost, so tie rows prove nothing)."""
        exact = self.bin_rows(x)
        ok = np.ones(len(x), bool)
        for f, tab in enumerate(self.tables):
            if tab.kind != "num" or not len(tab.thresholds):
                continue
            v = x[:, f]
            isnan = np.isnan(v)
            # mirror bin_rows_device: f32 value vs f32 threshold table;
            # NaN takes the f64-derived na/zero fallback, never f32 ops
            b32 = np.searchsorted(
                tab.thresholds.astype(np.float32),
                np.where(isnan, 0.0, v).astype(np.float32),
                side="left").astype(np.int64)
            nan_bin = tab.na_bin if tab.miss_nan else np.searchsorted(
                tab.thresholds, 0.0, side="left")
            b32 = np.where(isnan, nan_bin, b32)
            ok &= b32 == exact[:, f]
        return ok

    def self_check(self, max_rows: int = 64,
                   max_total_rows: int = 4096,
                   device_binning: bool = False) -> bool:
        """Post-build parity canary: traverse deterministic probe
        batches on the device and require the scores to be
        byte-identical to the host tree walk
        (``Tree.predict_leaf`` leaves fed through the SAME
        :meth:`raw_scores` accumulation, so the comparison isolates
        exactly the device traversal + binning).  Probes run in
        ``max_rows`` chunks until EVERY feature's candidate list has
        cycled through (capped at ``max_total_rows`` for pathological
        models), so all thresholds are exercised, not just the first
        chunk's worth.  ``device_binning`` additionally verifies the
        f32 on-device binning path the server will actually use under
        ``serve_device_binning`` — restricted to probe rows where f32
        and f64 binning provably agree (tie rows are the mode's
        documented inexactness, not an engine defect); a model device
        binning cannot represent at all (categoricals) raises
        :class:`EngineUnsupported` out of this check, which
        registry.load treats as failed.  True = verified; False = the
        compiled artifact disagrees with the model it was built from
        (a flattening bug, a device numeric surprise) — callers fall
        back to the host walk rather than serve wrong predictions
        (serve/registry.py)."""
        cands = self._probe_candidates()
        if not cands or not self.trees:
            return True
        total = min(max(len(c) for c in cands), max_total_rows)
        for off in range(0, total, max_rows):
            rows = min(max_rows, total - off)
            probe = np.zeros((rows, self.num_features), np.float64)
            idx = off + np.arange(rows)
            for f, c in enumerate(cands):
                probe[:, f] = c[idx % len(c)]
            host_leaves = np.stack(
                [t.predict_leaf(probe) for t in self.trees],
                axis=1).astype(np.int32)
            host = self.raw_scores(probe, leaves=host_leaves)
            if not np.array_equal(self.raw_scores(probe), host):
                return False
            if device_binning:
                mask = self._f32_consensus_mask(probe)
                if mask.any() and not np.array_equal(
                        self.raw_scores(probe[mask],
                                        device_binning=True),
                        host[mask]):
                    return False
        return True

    # -- introspection -----------------------------------------------------
    def compile_stats(self) -> dict:
        """Bucketed-compile-cache ledger: buckets used (with hit
        counts), the bound on distinct traversal shapes, and the
        process-wide forest trace counter
        (``predict_device.forest_trace_count``)."""
        from ..predict_device import forest_trace_count
        with self._lock:
            buckets = dict(sorted(self._buckets_seen.items()))
        cap = self.max_batch or max(list(buckets) + [self.min_bucket])
        import math
        bound = int(math.ceil(math.log2(max(cap, 2)))) + 1
        return {"fingerprint": self.fingerprint, "buckets": buckets,
                "max_compiles_bound": bound,
                "forest_traces_process": forest_trace_count(),
                "steps": self._steps, "num_trees": len(self.trees)}

    @classmethod
    def from_booster(cls, booster, *, max_batch: Optional[int] = None,
                     min_bucket: int = 16) -> "PredictorEngine":
        """Flatten a ``Booster`` (live or loaded from a model file)."""
        return cls(booster.trees, booster.tree_weights,
                   booster._num_tree_per_iteration,
                   booster.num_feature(),
                   objective=getattr(booster, "objective", None),
                   average_output=booster._average_output,
                   max_batch=max_batch, min_bucket=min_bucket)
