"""Micro-batching request queue with bounded backpressure.

One worker thread coalesces concurrent prediction requests into device
batches: the first queued request opens a window of ``max_wait_ms``;
everything that arrives before the window closes (or before the batch
reaches ``max_batch`` rows) rides the same traversal.  The queue is
BOUNDED in rows — when ``queue_rows`` of work is already pending,
``submit`` rejects immediately with :class:`BacklogFull` carrying a
``retry_after_ms`` estimate instead of growing without bound (the
explicit reject-with-retry-after discipline; HTTP maps it to 429 +
``Retry-After``).  Transient device errors retry through
``utils/resilience.RetryPolicy``; non-transient errors fail only the
requests of the batch that hit them.

Hardening (docs/Serving.md "Hardening"): per-request DEADLINES
(``deadline_ms`` / ``default_deadline_ms``) are enforced before any
device work — fail-fast at admission when the queue's estimated wait
already blows the deadline, and load-shedding at dispatch for requests
whose deadline lapsed while queued (:class:`DeadlineExceeded`).  An
optional CIRCUIT BREAKER (serve/breaker.py) rejects at admission while
the device side is failing; batch outcomes feed it from ``_dispatch``.
``begin_drain`` / ``wait_idle`` give graceful shutdown: queued work
finishes, new work is refused with :class:`BatcherDraining`.

Metrics (when a registry is attached): ``serve.queue_depth`` gauge
(rows), ``serve.batch_rows`` / ``serve.batch_occupancy`` /
``serve.latency`` histograms, ``serve.requests`` / ``serve.rows`` /
``serve.rejected`` / ``serve.errors`` / ``serve.deadline_rejected`` /
``serve.deadline_shed`` counters (breaker: ``serve.breaker_*``), plus
a ``serve.batch`` span per dispatched batch on the tracer.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..utils.resilience import (RetryPolicy, is_retryable_device_error,
                                retry_call)


class BacklogFull(RuntimeError):
    """Queue is at capacity; retry after ``retry_after_ms``."""

    def __init__(self, retry_after_ms: float, depth_rows: int):
        super().__init__(
            f"serve queue full ({depth_rows} rows pending); "
            f"retry in ~{retry_after_ms:.0f} ms")
        self.retry_after_ms = float(retry_after_ms)
        self.depth_rows = int(depth_rows)


class BatcherClosed(RuntimeError):
    """The batcher was shut down before this request completed."""


class BatcherDraining(BatcherClosed):
    """The batcher is draining (graceful shutdown): queued work will
    finish, new work is refused."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it could be served.

    Raised in two places, both BEFORE any device work is spent on the
    doomed request: at admission, when the queue's estimated wait
    already exceeds the deadline (fail fast instead of queuing work the
    client will have abandoned), and at dispatch, when a queued
    request's deadline lapsed while it waited (load shedding — the
    batch traverses only rows someone is still waiting for)."""

    def __init__(self, deadline_ms: float, waited_ms: float,
                 where: str = "queue"):
        super().__init__(
            f"deadline of {deadline_ms:.0f} ms exceeded in {where} "
            f"(waited {waited_ms:.0f} ms)")
        self.deadline_ms = float(deadline_ms)
        self.waited_ms = float(waited_ms)
        self.where = where


class PredictionFuture:
    """Handle for one submitted request; ``result()`` blocks."""

    __slots__ = ("_event", "_value", "_exc", "info", "t_submit",
                 "deadline")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None
        self.info: dict = {}
        self.t_submit = time.perf_counter()
        self.deadline: Optional[float] = None   # absolute perf_counter

    def _set(self, value, info: Optional[dict] = None) -> None:
        self._value = value
        if info:
            self.info = info
        self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("prediction did not complete in time")
        if self._exc is not None:
            raise self._exc
        return self._value


class _Item:
    __slots__ = ("rows", "future", "probe", "key")

    def __init__(self, rows: np.ndarray, future: PredictionFuture,
                 probe: bool = False, key=None):
        self.rows = rows
        self.future = future
        # this request claimed the breaker's half-open probe slot: if it
        # leaves without a batch outcome the slot must be released
        self.probe = probe
        # routing key (e.g. a fleet segment): requests with different
        # keys never share a batch — each key may resolve to a
        # different model
        self.key = key


class MicroBatcher:
    """Coalesce concurrent requests into bounded device batches.

    ``predict_fn(rows) -> (outputs, info)``: outputs is an array whose
    leading axis matches ``rows`` (sliced back per request), ``info`` a
    small dict attached to every future of the batch (model version
    etc.); a plain-array return is also accepted.

    Lock contract (tools/analyze/check_races.py):
        _lock guards: _queue, _depth_rows, _closed, _draining
        _lock guards: _inflight, _ewma_batch_s
        breaker type: lightgbm_tpu/serve/breaker.py:ServeBreaker

    ``_wake`` is a Condition over ``_lock`` (one mutex).  The breaker
    is called both under ``_lock`` (admission, shed) and outside it
    (batch outcomes) — legal because the breaker's own lock is
    leaf-level and never calls back into the batcher.
    ``batches_dispatched`` is written by the worker thread only.
    """

    # how far before the earliest queued deadline the coalescing window
    # closes: absorbs condition-wakeup + collect latency so the request
    # dispatches while still inside its deadline rather than being shed
    # microseconds past it
    _DISPATCH_MARGIN_S = 0.005

    def __init__(self, predict_fn: Callable, *, max_batch: int = 1024,
                 max_wait_ms: float = 2.0, queue_rows: int = 8192,
                 retry_policy: Optional[RetryPolicy] = None,
                 default_deadline_ms: float = 0.0, breaker=None,
                 metrics=None, tracer=None):
        self.predict_fn = predict_fn
        self.max_batch = max(1, int(max_batch))
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.queue_rows = max(self.max_batch, int(queue_rows))
        self.retry_policy = retry_policy
        self.default_deadline_ms = max(0.0, float(default_deadline_ms))
        self.breaker = breaker
        self.metrics = metrics
        self.tracer = tracer
        self._queue: List[_Item] = []
        self._depth_rows = 0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        self._inflight = False
        self.batches_dispatched = 0
        # EWMA of observed per-batch service time (seconds), written by
        # the worker after each batch and read by submit — both under
        # _lock; 0 until the first batch completes
        self._ewma_batch_s = 0.0
        self._worker = threading.Thread(target=self._run,
                                        name="lgbtpu-serve-batcher",
                                        daemon=True)
        self._worker.start()

    # -- client side -------------------------------------------------------
    def submit(self, rows: np.ndarray,
               deadline_ms: Optional[float] = None,
               key=None) -> PredictionFuture:
        """Enqueue one request; raises :class:`BacklogFull` when the
        bounded queue cannot take it, :class:`CircuitOpen` while the
        serving circuit is open, and :class:`DeadlineExceeded` when the
        queue's estimated wait already exceeds ``deadline_ms`` (which
        defaults to ``default_deadline_ms``; <= 0 means no deadline).
        A 1-D vector is one row; anything not coercible to a 2-D array
        is rejected HERE, where the error reaches only the offending
        caller — malformed rows must never travel into a shared batch
        where they would poison the other requests riding it."""
        rows = np.asarray(rows)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.ndim != 2:
            raise ValueError(f"rows must be 2-D, got {rows.ndim}-D")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline_ms = float(deadline_ms)
        n = len(rows)
        fut = PredictionFuture()
        if deadline_ms > 0:
            fut.deadline = fut.t_submit + deadline_ms / 1e3
        with self._lock:
            if self._closed:
                raise BatcherClosed("batcher is closed")
            if self._draining:
                raise BatcherDraining("batcher is draining")
            pending_batches = -(-self._depth_rows // self.max_batch)
            window_ms = pending_batches * max(
                self.max_wait_ms_effective(), 1.0)
            # the wait estimate: measured per-batch service time once
            # any batch has completed (full batches dispatch on FILL,
            # so the coalescing window is not a wait floor for them —
            # a drained-in-1ms queue must not 504 a 5ms deadline), the
            # window heuristic until then (cold start: reject on the
            # only signal there is)
            ewma_ms = self._ewma_batch_s * 1e3
            est_wait_ms = pending_batches * ewma_ms if ewma_ms > 0 \
                else window_ms
            if self._depth_rows + n > self.queue_rows and self._queue:
                if self.metrics is not None:
                    self.metrics.counter("serve.rejected").inc()
                raise BacklogFull(max(est_wait_ms, window_ms),
                                  self._depth_rows)
            if fut.deadline is not None and self._queue \
                    and est_wait_ms > deadline_ms:
                # the estimated wait already blows the deadline: fail
                # fast instead of queuing work the client will have
                # abandoned
                if self.metrics is not None:
                    self.metrics.counter("serve.deadline_rejected").inc()
                raise DeadlineExceeded(deadline_ms, 0.0,
                                       where="admission")
            probe = False
            if self.breaker is not None:
                # LAST admission check, after every other rejection:
                # check_admission in HALF_OPEN claims the single probe
                # slot, and a later BacklogFull/DeadlineExceeded would
                # leak it — rejecting ALL traffic for a full (possibly
                # doubled) cooldown on an already-healthy device.  Still
                # before enqueue: breaker-rejected work never consumes
                # queue capacity or waits out a doomed retry cycle
                probe = self.breaker.check_admission()
            self._queue.append(_Item(rows, fut, probe=probe, key=key))
            self._depth_rows += n
            if self.metrics is not None:
                self.metrics.gauge("serve.queue_depth").set(
                    self._depth_rows)
            self._wake.notify()
        return fut

    def max_wait_ms_effective(self) -> float:
        return self.max_wait_s * 1e3

    @property
    def depth_rows(self) -> int:
        with self._lock:
            return self._depth_rows

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining and not self._closed

    def begin_drain(self) -> None:
        """Stop accepting work (``submit`` raises
        :class:`BatcherDraining`) while the worker keeps draining what
        is already queued.  Reversible shutdown prologue: the batcher
        itself stays alive until :meth:`close`."""
        with self._lock:
            self._draining = True
            self._wake.notify_all()

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until the queue is empty AND no batch is in flight;
        False if ``timeout`` elapsed first.  With :meth:`begin_drain`
        active this is "drained": every accepted request has been
        answered."""
        end = None if timeout is None else time.perf_counter() + timeout
        with self._lock:
            while self._queue or self._inflight:
                left = None if end is None \
                    else end - time.perf_counter()
                if left is not None and left <= 0:
                    return False
                self._wake.wait(left)
            return True

    def close(self, timeout: float = 5.0) -> None:
        """Graceful shutdown: new submissions are rejected immediately,
        already-queued work drains, and only requests the worker could
        not drain within ``timeout`` fail with :class:`BatcherClosed`."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._draining = True
            self._wake.notify_all()
        self._worker.join(timeout)
        with self._lock:
            leftovers, self._queue = self._queue, []
            self._depth_rows = 0
        for item in leftovers:
            if item.probe and self.breaker is not None:
                self.breaker.on_dropped()
            item.future._set_exception(BatcherClosed("batcher closed"))

    # -- worker side -------------------------------------------------------
    def _collect(self) -> List[_Item]:
        """Block for the next batch: wait for a first request, then hold
        the window open until ``max_wait_s`` passes or ``max_batch``
        rows are in hand.  An oversized single request becomes its own
        batch (the engine chunks internally).  Requests whose deadline
        lapsed while queued are shed here — failed with
        :class:`DeadlineExceeded` instead of riding the batch, so
        device time goes only to rows someone is still waiting for."""
        expired: List[_Item] = []
        with self._lock:
            while not self._queue and not self._closed:
                self._wake.wait()
            if not self._queue:
                return []
            # the window never holds past a queued request's deadline:
            # close it early (margin covers wakeup + collect latency)
            # and dispatch, instead of sleeping the full coalescing
            # window and then shedding work the window itself doomed.
            # Each arrival notifies and may carry a tighter deadline —
            # but the worker is the ONLY popper and it is here, so the
            # queue is append-only for the duration of the window and
            # each pass need only fold in the arrivals since the last
            # (O(1) amortized per request, not O(queue) per wakeup)
            end = self._queue[0].future.t_submit + self.max_wait_s
            have = 0
            scanned = 0
            while not self._closed:
                for item in self._queue[scanned:]:
                    have += len(item.rows)
                    d = item.future.deadline
                    if d is not None:
                        end = min(end, d - self._DISPATCH_MARGIN_S)
                scanned = len(self._queue)
                if have >= self.max_batch:
                    break
                left = end - time.perf_counter()
                if left <= 0:
                    break
                self._wake.wait(left)
            batch: List[_Item] = []
            rows = 0
            now = time.perf_counter()
            while self._queue:
                head = self._queue[0]
                if head.future.deadline is not None \
                        and now > head.future.deadline:
                    self._queue.pop(0)
                    self._depth_rows -= len(head.rows)
                    expired.append(head)
                    continue
                nxt = len(head.rows)
                if batch and (rows + nxt > self.max_batch
                              or head.rows.shape[1]
                              != batch[0].rows.shape[1]
                              or head.key != batch[0].key):
                    # width mismatch (a request sized for a different
                    # model width) or a different routing key (a
                    # request bound for a different model): never
                    # concatenated into this batch — it opens the NEXT
                    # batch and fails alone if invalid
                    break
                item = self._queue.pop(0)
                batch.append(item)
                rows += nxt
            self._depth_rows -= rows
            if expired:
                # shed futures are failed BEFORE the all-shed wakeup
                # below: wait_idle returning True means every accepted
                # request has been ANSWERED, not merely dequeued — a
                # drain caller must never observe "drained" while shed
                # clients still block in result().  (Holding the lock
                # here is fine: _set_exception only sets an Event, and
                # breaker calls under the batcher lock are the
                # established submit-side ordering.)
                if self.metrics is not None:
                    self.metrics.counter("serve.deadline_shed").inc(
                        len(expired))
                for item in expired:
                    if item.probe and self.breaker is not None:
                        # a shed probe never reaches _dispatch: release
                        # the slot or the breaker stays shut until
                        # expiry
                        self.breaker.on_dropped()
                    f = item.future
                    f._set_exception(DeadlineExceeded(
                        (f.deadline - f.t_submit) * 1e3,
                        (now - f.t_submit) * 1e3, where="queue"))
            if batch:
                self._inflight = True
            elif expired:
                # everything collected this round was shed: no dispatch
                # will follow, so wake wait_idle() here — otherwise a
                # drain whose last round is all-expired sleeps out its
                # full budget
                self._wake.notify_all()
            if self.metrics is not None:
                self.metrics.gauge("serve.queue_depth").set(
                    self._depth_rows)
        return batch

    def _record_service_time(self, t0: float) -> None:
        # failed batches count too: their (retry-inflated) duration is
        # exactly what the next queued request will wait through
        dur = time.perf_counter() - t0
        with self._lock:        # submit reads the EWMA under the lock
            prev = self._ewma_batch_s
            self._ewma_batch_s = dur if prev == 0.0 \
                else 0.25 * dur + 0.75 * prev

    def _dispatch(self, batch: List[_Item]) -> None:
        n = sum(len(i.rows) for i in batch)
        t0 = time.perf_counter()
        span = (self.tracer.span("serve.batch", rows=n,
                                 requests=len(batch))
                if self.tracer is not None else None)
        try:
            # concatenation INSIDE the guarded region: any surviving
            # shape surprise fails this batch's futures, never the
            # worker thread
            rows = (batch[0].rows if len(batch) == 1
                    else np.concatenate([i.rows for i in batch], axis=0))
            if batch[0].key is not None:
                # keyed batch: the whole batch shares one routing key
                # (collect never mixes keys), delivered to predict_fn
                # so it can resolve the routed model
                out = retry_call(self.predict_fn, rows, batch[0].key,
                                 policy=self.retry_policy,
                                 classify=is_retryable_device_error,
                                 label="serve.predict")
            else:
                out = retry_call(self.predict_fn, rows,
                                 policy=self.retry_policy,
                                 classify=is_retryable_device_error,
                                 label="serve.predict")
            outputs, info = out if isinstance(out, tuple) else (out, {})
            outputs = np.asarray(outputs)
        except BaseException as e:
            self._record_service_time(t0)
            if span is not None:
                span.end()
            if self.metrics is not None:
                self.metrics.counter("serve.errors").inc(len(batch))
            if self.breaker is not None:
                self.breaker.on_failure(
                    e, probe=any(i.probe for i in batch))
            for item in batch:
                item.future._set_exception(e)
            return
        self._record_service_time(t0)
        if span is not None:
            span.end()
        if self.breaker is not None:
            self.breaker.on_success()
        self.batches_dispatched += 1
        now = time.perf_counter()
        if self.metrics is not None:
            self.metrics.counter("serve.requests").inc(len(batch))
            self.metrics.counter("serve.rows").inc(n)
            self.metrics.histogram("serve.batch_rows").observe(n)
            self.metrics.histogram("serve.batch_occupancy").observe(
                min(1.0, n / self.max_batch))
            for item in batch:
                self.metrics.histogram("serve.latency").observe(
                    now - item.future.t_submit)
        lo = 0
        for item in batch:
            hi = lo + len(item.rows)
            item.future._set(outputs[lo:hi], dict(info))
            lo = hi

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                with self._lock:
                    if self._closed:
                        return
                continue
            try:
                self._dispatch(batch)
            except BaseException as e:       # noqa: BLE001 — the worker
                # must outlive ANY single batch; _dispatch already fails
                # the batch's own futures, this is the last-ditch belt
                for item in batch:
                    if not item.future.done():
                        item.future._set_exception(e)
            finally:
                with self._lock:
                    self._inflight = False
                    self._wake.notify_all()     # wake wait_idle()
