"""sklearn-API tests (test_sklearn.py analog, SURVEY.md §4)."""

import numpy as np
import pytest

from lightgbm_tpu.sklearn import LGBMClassifier, LGBMRanker, LGBMRegressor
from lightgbm_tpu.metrics import _auc


class TestRegressor:
    def test_fit_predict(self, regression_data):
        x, y = regression_data
        m = LGBMRegressor(n_estimators=30, num_leaves=15, max_bin=63,
                          random_state=0)
        m.fit(x[:3000], y[:3000])
        pred = m.predict(x[3000:])
        mse = np.mean((pred - y[3000:]) ** 2)
        assert mse < 0.5 * np.var(y[3000:])
        assert m.n_features_in_ == x.shape[1]
        assert len(m.feature_importances_) == x.shape[1]
        assert m.feature_importances_.sum() > 0

    def test_get_set_params(self):
        m = LGBMRegressor(num_leaves=7)
        p = m.get_params()
        assert p["num_leaves"] == 7
        m.set_params(num_leaves=63, learning_rate=0.3)
        assert m.num_leaves == 63
        assert m.learning_rate == 0.3

    def test_regularization_params(self, regression_data):
        x, y = regression_data
        m = LGBMRegressor(n_estimators=5, num_leaves=15, reg_alpha=1.0,
                          reg_lambda=5.0, max_bin=31)
        m.fit(x[:1000], y[:1000])
        assert np.isfinite(m.predict(x[:50])).all()


class TestClassifier:
    def test_binary(self, binary_data):
        x, y = binary_data
        m = LGBMClassifier(n_estimators=20, num_leaves=15, max_bin=63)
        m.fit(x[:3000], y[:3000])
        assert set(m.classes_) == {0.0, 1.0}
        proba = m.predict_proba(x[3000:])
        assert proba.shape == (1000, 2)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
        pred = m.predict(x[3000:])
        assert (pred == y[3000:]).mean() > 0.88

    def test_multiclass_string_labels(self):
        rs = np.random.RandomState(0)
        x = rs.randn(1500, 6)
        y_num = (x[:, 0] > 0.5).astype(int) + (x[:, 1] > 0).astype(int)
        y = np.array(["a", "b", "c"])[y_num]
        m = LGBMClassifier(n_estimators=10, num_leaves=15, max_bin=31)
        m.fit(x, y)
        assert list(m.classes_) == ["a", "b", "c"]
        pred = m.predict(x[:100])
        assert set(pred) <= {"a", "b", "c"}
        assert (pred == y[:100]).mean() > 0.7

    def test_class_weight_balanced(self, binary_data):
        x, y = binary_data
        m = LGBMClassifier(n_estimators=10, num_leaves=7, max_bin=31,
                           class_weight="balanced")
        m.fit(x, y)
        assert np.isfinite(m.predict_proba(x[:10])).all()

    def test_class_weight_dict_keys_original_labels(self, binary_data):
        """ADVICE r5 #3: class_weight dict entries are keyed by the
        ORIGINAL label values ({1,2}, strings), not the encoded class
        index — a {1: .., 2: ..} dict on {1,2} labels must behave exactly
        like the equivalent per-row sample_weight, not be dropped."""
        x, y01 = binary_data
        y = y01.astype(int) + 1                      # labels {1, 2}
        cw = {1: 1.0, 2: 7.0}
        m_cw = LGBMClassifier(n_estimators=8, num_leaves=7, max_bin=31,
                              class_weight=cw)
        m_cw.fit(x, y)
        sw = np.where(y == 2, 7.0, 1.0)
        m_sw = LGBMClassifier(n_estimators=8, num_leaves=7, max_bin=31)
        m_sw.fit(x, y, sample_weight=sw)
        np.testing.assert_allclose(m_cw.predict_proba(x[:200]),
                                   m_sw.predict_proba(x[:200]),
                                   rtol=1e-5, atol=1e-6)
        # ...and an un-weighted fit must differ (the weights were applied)
        m_un = LGBMClassifier(n_estimators=8, num_leaves=7, max_bin=31)
        m_un.fit(x, y)
        assert not np.allclose(m_cw.predict_proba(x[:200]),
                               m_un.predict_proba(x[:200]))

    def test_class_weight_dict_string_labels(self):
        rs = np.random.RandomState(2)
        x = rs.randn(800, 5)
        y = np.where(x[:, 0] > 0, "pos", "neg")
        m = LGBMClassifier(n_estimators=5, num_leaves=7, max_bin=31,
                           class_weight={"pos": 3.0, "neg": 1.0})
        m.fit(x, y)
        sw = np.where(y == "pos", 3.0, 1.0)
        m_sw = LGBMClassifier(n_estimators=5, num_leaves=7, max_bin=31)
        m_sw.fit(x, y, sample_weight=sw)
        np.testing.assert_allclose(m.predict_proba(x[:100]),
                                   m_sw.predict_proba(x[:100]),
                                   rtol=1e-5, atol=1e-6)

    def test_eval_set_early_stopping(self, binary_data):
        x, y = binary_data
        m = LGBMClassifier(n_estimators=200, num_leaves=31, max_bin=63,
                           metric="auc")
        rs = np.random.RandomState(7)
        m.fit(x[:3000], y[:3000],
              eval_set=[(x[3000:], rs.permutation(y[3000:]))],
              early_stopping_rounds=3)
        assert m.best_iteration_ > 0
        assert m.n_estimators_ < 200


class TestRanker:
    def test_lambdarank(self):
        rs = np.random.RandomState(0)
        n_q, q_size = 60, 20
        n = n_q * q_size
        x = rs.randn(n, 8)
        rel = 2.0 * x[:, 0] + x[:, 1] + 0.3 * rs.randn(n)
        # graded relevance 0..4 per query
        y = np.zeros(n, np.int32)
        for q in range(n_q):
            s = slice(q * q_size, (q + 1) * q_size)
            ranks = np.argsort(np.argsort(-rel[s]))
            y[s] = np.clip(4 - ranks // 4, 0, 4)
        group = [q_size] * n_q
        m = LGBMRanker(n_estimators=20, num_leaves=15, max_bin=63,
                       min_child_samples=5)
        m.fit(x, y, group=group)
        pred = m.predict(x)
        # within-query ordering should correlate with relevance
        corr = np.corrcoef(pred, rel)[0, 1]
        assert corr > 0.5, f"rank correlation too low: {corr}"

    def test_requires_group(self):
        with pytest.raises(ValueError):
            LGBMRanker().fit(np.zeros((10, 2)), np.zeros(10))


def test_sklearn_result_attributes():
    import numpy as np
    from lightgbm_tpu.sklearn import LGBMClassifier
    rs = np.random.RandomState(0)
    x = rs.randn(800, 5)
    y = (x[:, 0] > 0).astype(int)
    clf = LGBMClassifier(n_estimators=6, num_leaves=7, verbosity=-1)
    clf.fit(x, y, eval_set=[(x[:200], y[:200])])
    assert clf.fitted_ is True
    assert clf.n_iter_ == 6
    assert clf.objective_ == "binary"
    er = clf.evals_result_
    assert "valid_0" in er and any(len(v) == 6 for v in er["valid_0"].values())
