"""Booster: the user-facing model handle.

Analog of the reference python-package ``Booster`` (basic.py:2548) fused
with the C-API Booster wrapper (c_api.cpp:106) — in this TPU-native rebuild
there is no C shim between them, the Booster drives the device boosting
model directly.  Model (de)serialization follows the reference text format
(``GBDT::SaveModelToString`` / ``LoadModelFromString``,
/root/reference/src/boosting/gbdt_model_text.cpp:311, 421) so models
round-trip and remain ecosystem-readable.
"""

from __future__ import annotations

import io
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .config import Config
from .dataset import Dataset
from .metrics import Metric, create_metric
from .models import create_boosting
from .objectives import create_objective
from .tree_model import Tree


def _objective_to_string(cfg: Config) -> str:
    o = cfg.objective
    if o == "binary":
        return f"binary sigmoid:{cfg.sigmoid:g}"
    if o in ("multiclass", "multiclassova"):
        return f"{o} num_class:{cfg.num_class}"
    if o == "lambdarank":
        return "lambdarank"
    if o == "quantile":
        return f"quantile alpha:{cfg.alpha:g}"
    if o == "huber":
        return f"huber alpha:{cfg.alpha:g}"
    if o == "fair":
        return f"fair fair_c:{cfg.fair_c:g}"
    if o == "tweedie":
        return f"tweedie tweedie_variance_power:{cfg.tweedie_variance_power:g}"
    return o


def _objective_from_string(s: str) -> Dict[str, Any]:
    toks = s.split()
    out: Dict[str, Any] = {"objective": toks[0]} if toks else {}
    for t in toks[1:]:
        if ":" in t:
            k, v = t.split(":", 1)
            out[k] = v
    return out


def _finalize_score(score: np.ndarray, k: int, objective, average_output,
                    t0: int, t1: int, raw_score: bool) -> np.ndarray:
    """The ONE score-finalization tail shared by every predict path
    (host walk, bucketed engine, serve engine): RF averaging over the
    predicted range, then the objective's output conversion.  Byte-
    identical results across paths depend on this being a single
    definition — do not inline copies."""
    if average_output and t1 > t0:
        score /= (t1 - t0) // k
    if not raw_score and objective is not None:
        import jax.numpy as jnp
        conv = objective.convert_output(
            jnp.asarray(score if k > 1 else score[:, 0]))
        return np.asarray(conv)
    return score if k > 1 else score[:, 0]


class _IntAndCall(int):
    """int that also answers the reference's METHOD spelling — basic.py
    exposes ``bst.current_iteration()`` as a method while this framework
    grew it as an attribute; a callable int serves both."""

    def __call__(self) -> int:
        return int(self)


class Booster:
    """Training/prediction handle (basic.py:2548 / boosting.h:27 analog)."""

    def __init__(self, params: Optional[Dict] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None,
                 hist_reduce=None):
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._valid_names: List[str] = []
        self._train_metrics: List[Metric] = []
        self._valid_metrics: List[List[Metric]] = []
        self.trees: List[Tree] = []
        self.tree_weights: List[float] = []
        self.feature_names: List[str] = []
        self.pandas_categorical = None
        self._model = None
        self.train_set = None
        self._num_class = 1
        self._num_tree_per_iteration = 1
        self._average_output = False
        self._max_feature_idx = 0
        # bucketed predictor engine (serve/engine.py), built lazily by
        # predict(); False = engine refused this model (don't retry),
        # None = not built yet.  Dropped on every model mutation.
        self._engine_cache = None

        if model_file is not None:
            # utf-8 to match the write side (atomic_write / snapshot
            # checksums hash utf-8 bytes); the locale default would
            # desynchronize read and write on non-utf-8 hosts
            with open(model_file, encoding="utf-8") as f:
                self._load_model_string(f.read())
            return
        if model_str is not None:
            self._load_model_string(model_str)
            return
        if train_set is None:
            raise ValueError("Booster needs train_set, model_file or model_str")

        self.config = Config(params or {})
        # persistent-compile-cache bring-up + compile counters: every
        # training Booster warm-starts its jit compiles from (and
        # contributes to) the on-disk cache unless compile_cache=false;
        # a pre-set JAX_COMPILATION_CACHE_DIR is respected
        from .utils.compile_cache import maybe_enable_from_config
        maybe_enable_from_config(self.config)
        # reference _update_params semantics (basic.py: train-time params
        # are update()d ONTO the dataset's own params): a not-yet-
        # constructed dataset bins with its OWN params as the base and
        # the booster's params overriding — a Dataset(params={'max_bin':
        # 63}) keeps its 63 bins when the booster params don't mention
        # binning.  The C API relies on this: LGBM_DatasetCreateFromMat
        # carries the binning params, LGBM_BoosterCreate the training
        # params (c_api.cpp bins at dataset-create time).
        construct_cfg = self.config
        if not train_set._constructed and train_set.params:
            from .config import canonical_params
            construct_cfg = Config({**canonical_params(train_set.params),
                                    **canonical_params(params or {})})
        self.train_set = train_set.construct(construct_cfg)
        self.objective = create_objective(self.config)
        self._model = create_boosting(self.config, self.train_set,
                                      self.objective, hist_reduce)
        self._num_class = self.config.num_class
        self._num_tree_per_iteration = self.config.num_model_per_iteration
        self._average_output = getattr(self._model, "average_output", False)
        self.feature_names = list(self.train_set.feature_names)
        self._max_feature_idx = self.train_set.num_total_features - 1

        self._train_metrics = self._make_metrics(self.train_set.metadata,
                                                 self.train_set.num_data)

    # ------------------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if self._model is None:
            raise ValueError("cannot add validation data to a loaded model")
        data.reference = self.train_set
        data.construct(self.config)
        self._model.add_valid_set(data)
        self._valid_names.append(name)
        self._valid_metrics.append(self._make_metrics(data.metadata,
                                                      data.num_data))
        return self

    def _make_metrics(self, metadata, num_data) -> List:
        """Configured metric objects bound to one dataset's metadata."""
        ms = []
        for mname in self.config.default_metric():
            m = create_metric(mname, self.config)
            if m is not None:
                m.init(metadata, num_data)
                ms.append(m)
        return ms

    def update(self, train_set=None, fobj=None) -> bool:
        """One boosting iteration; returns True if no further splits
        (LGBM_BoosterUpdateOneIter analog, c_api.cpp:1686)."""
        if fobj is not None:
            preds = self._model.train_score()
            if self._num_tree_per_iteration == 1:
                preds = preds[:, 0]
            grad, hess = fobj(preds, self.train_set)
            grad, hess = np.asarray(grad), np.asarray(hess)
            n = self.train_set.num_data
            k = self._num_tree_per_iteration
            if grad.size != hess.size:
                raise ValueError(
                    f"Lengths of gradient ({grad.size}) and Hessian "
                    f"({hess.size}) don't match")
            if grad.size != n * k:
                # reference-exact message shape (basic.py __boost)
                raise ValueError(
                    f"Lengths of gradient ({grad.size}) and Hessian "
                    f"({hess.size}) don't match training data length "
                    f"({n}) * number of models per one iteration ({k})")
            if k > 1 and grad.ndim == 1:
                # flat multiclass gradients arrive CLASS-major (the
                # reference C convention, basic.py __boost F-ravel);
                # internal layout is [n, k]
                grad = grad.reshape(k, n).T
                hess = hess.reshape(k, n).T
            stopped = self._model.train_one_iter(grad, hess)
        else:
            stopped = self._model.train_one_iter()
        self._sync_trees()
        return stopped

    def update_chunk(self, k: int) -> bool:
        """Run ``k`` iterations fused in one device program (one host
        round trip per chunk — see GBDTModel.train_chunk).  Caller must
        have checked ``supports_fused()``; returns True if training hit a
        no-split iteration."""
        stopped = self._model.train_chunk(k)
        self._sync_trees()
        return stopped

    def update_superepoch(self, k: int, es_it0: int, eval_spec=(),
                          es_spec=None) -> dict:
        """Run ``k`` FULL iterations — growth, score updates, valid-set
        scoring, traced metric eval, early-stop vote — fused in one
        device program with ONE host fetch (GBDTModel.train_superepoch).
        Returns the fetched replay block for engine.train's host-side
        callback replay."""
        out = self._model.train_superepoch(k, es_it0, eval_spec, es_spec)
        self._sync_trees()
        return out

    def supports_fused(self) -> bool:
        return (self._model is not None
                and hasattr(self._model, "supports_fused")
                and self._model.supports_fused()
                and not self._model.valid_sets)

    def fused_reasons(self) -> List[str]:
        """Why ``supports_fused()`` is False — specific blockers, empty
        when fusion is eligible (GBDTModel.fused_reasons; bench
        provenance and error messages)."""
        if self._model is None or not hasattr(self._model,
                                              "fused_reasons"):
            return ["no active training model"]
        return self._model.fused_reasons()

    def eval_valid_traced(self) -> List[Tuple]:
        """Every valid-set metric evaluated by the TRACED metric kernels
        in one jitted program + ONE host fetch — the SAME program
        (metrics.build_traced_eval) the super-epoch replay reports
        through, so a ``fused_eval=true`` per-iteration run produces
        bit-identical eval values to a super-epoch run (the
        byte-identity contract the tests pin); the host f64 ``eval_*``
        path stays available via ``fused_eval=false``."""
        m = self._model
        spec = tuple(
            (vi, name, mt.name, mt.is_higher_better)
            for vi, name in enumerate(self._valid_names)
            for mt in self._valid_metrics[vi])
        fn = m._teval_fn(spec)
        svecs = tuple(vs[:, 0] for _, _, vs in m.valid_sets)
        ops = tuple(m._se_valid_dev(vi)
                    for vi in range(len(m.valid_sets)))
        vals = m._eget(fn(svecs, ops), "traced_eval")
        return [(name, mn, float(vals[e]), hib)
                for e, (vi, name, mn, hib) in enumerate(spec)]

    def rollback_one_iter(self) -> "Booster":
        self._model.rollback_one_iter()
        self._sync_trees()
        return self

    # -- telemetry (obs/ subsystem; docs/Observability.md) ----------------
    def telemetry_snapshot(self) -> dict:
        """Current metrics snapshot (deterministic dict).  With
        ``telemetry=false`` (the default) the obs metrics are absent but
        the process-wide compile accounting is still included —
        ``compile.count`` / ``compile.seconds`` (backend compiles),
        ``compile.cache_hits`` / ``compile.cache_misses`` (persistent
        cache), ``compile.traces`` (library jit traces) — so warm-start
        is observable, not assumed (docs/Compile-Cache.md).

        With telemetry on, the ``perf.*`` roofline keys join the
        static flop ledger with the fenced phase spans: per-phase
        flops / hbm_bytes (deterministic, dp == serial), achieved
        FLOP/s and bytes/s, MFU against the device peak table, and a
        compute-vs-memory ``bound`` verdict (obs/attrib.py,
        docs/Observability.md "Roofline & flight recorder").

        Returns a DEEP COPY: callers may mutate the result freely
        without corrupting the live registry/ledger state the next
        snapshot is built from.  Multi-process: per-shard obs
        registries are gathered and merged, so every process sees
        host 0's aggregated view."""
        import copy
        m = self._model
        obs = None if m is None else getattr(m, "_obs", None)
        snap = {} if obs is None else dict(obs.snapshot())
        from .utils.compile_cache import compile_snapshot
        snap.update(compile_snapshot())
        if obs is not None:
            # no-op (returns {}) unless flops.* counters exist — on a
            # multi-process pod the gathered snapshot carries host 0's
            # ledger counters, so every process derives the same keys
            from .obs.attrib import perf_summary
            snap.update(perf_summary(snap, peaks=obs.peaks))
        return copy.deepcopy(snap)

    def telemetry_finish(self) -> dict:
        """Stop any active profiler window, flush the JSONL trace sink,
        and return the final aggregated metrics snapshot."""
        m = self._model
        if m is None or getattr(m, "_obs", None) is None:
            return {}
        return m._obs.finish()

    def _sync_trees(self) -> None:
        self.trees = self._model.models
        self.tree_weights = self._model.tree_weights
        self._drop_predict_cache()

    def _drop_predict_cache(self) -> None:
        """Invalidate the cached predictor engine after any model
        mutation (training step, rollback, merge, shuffle, refit)."""
        self._engine_cache = None

    # auto mode's build threshold: rows x trees below this predicts
    # faster through the host walk than through a fresh XLA trace
    _ENGINE_AUTO_WORK = 1 << 16

    def predict_engine(self, n_rows: Optional[int] = None):
        """The bucketed SoA predictor engine for the CURRENT model
        (serve/engine.py), or None when ``predict_bucketed`` rules it
        out or the model shape is unsupported.  ``predict_bucketed``:
        ``auto`` (default) builds the engine once rows x trees is large
        enough to repay the trace — an engine already built (a larger
        earlier call, or serving installing its own) serves ALL sizes;
        ``true`` always builds; ``false`` never.  Cached until the
        model mutates."""
        mode = str(getattr(self.config, "predict_bucketed",
                           "auto")).lower()
        if mode in ("false", "0", "no", "off", "-"):
            return None
        eng = getattr(self, "_engine_cache", None)
        if eng is False:
            return None
        if eng is not None and len(eng.trees) != len(self.trees):
            eng = None                    # stale (defensive; _sync_trees
            #                               normally drops it)
        if eng is None:
            if mode == "auto" and (n_rows is None or n_rows *
                                   max(len(self.trees), 1)
                                   < self._ENGINE_AUTO_WORK):
                return None
            from .serve.engine import EngineUnsupported, PredictorEngine
            try:
                eng = PredictorEngine.from_booster(self)
            except EngineUnsupported as e:
                from .utils.log import Log
                Log.debug(f"bucketed predict disabled for this model: "
                          f"{e}")
                self._engine_cache = False
                return None
            self._engine_cache = eng
        return eng

    @property
    def current_iteration(self) -> "_IntAndCall":
        if self._model is not None:
            return _IntAndCall(self._model.num_iterations_trained)
        return _IntAndCall(len(self.trees) // self._num_tree_per_iteration)

    def num_trees(self) -> int:
        return len(self.trees)

    # -- pickling / copying (basic.py __getstate__: a Booster serializes
    #    as its model string — the live training state holds jitted
    #    device programs that cannot and should not be pickled) --------
    def __getstate__(self):
        return {"model_str": self.model_to_string(),
                "best_iteration": int(self.best_iteration),
                "best_score": dict(self.best_score)}

    def __setstate__(self, state):
        self.__init__(model_str=state["model_str"])
        self.best_iteration = state["best_iteration"]
        self.best_score = state["best_score"]

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, _memo):
        new = Booster(model_str=self.model_to_string())
        new.best_iteration = self.best_iteration
        new.best_score = dict(self.best_score)
        return new

    def num_model_per_iteration(self) -> int:
        return self._num_tree_per_iteration

    def num_feature(self) -> int:
        """Number of features the model was trained on (basic.py
        Booster.num_feature / LGBM_BoosterGetNumFeature)."""
        return self._max_feature_idx + 1

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """Re-set training parameters for FUTURE iterations
        (LGBM_BoosterResetParameter, src/c_api.cpp ResetConfig; Python
        basic.py reset_parameter).  Structural parameters that would
        require re-binning or a new grower (num_leaves, max_bin,
        tree_learner, ...) are rejected like the reference's
        ResetConfig does for dataset-coupled params."""
        if self._model is None:
            raise ValueError("reset_parameter needs an active training "
                             "Booster (not a loaded model)")
        # bagging_* is excluded: Config zeroes bagging_freq at construction
        # when all fractions are 1.0, so enabling bagging mid-training
        # would silently no-op — reject it instead of pretending
        allowed_now = {"learning_rate", "verbosity", "verbose",
                       "metric_freq", "feature_fraction",
                       "feature_fraction_seed", "first_metric_only",
                       # CEGB penalties are per-call grower inputs, so
                       # resetting them only needs the state rebuilt
                       # below (ResetConfig swaps the config the tree
                       # learner reads, c_api.cpp ResetConfig)
                       "cegb_tradeoff", "cegb_penalty_split",
                       "cegb_penalty_feature_coupled",
                       "cegb_penalty_feature_lazy"}
        from .config import _ALIASES, _coerce, _PARAMS
        cegb_touched = False
        for k, v in params.items():
            canon = _ALIASES.get(k, k)
            if canon not in allowed_now:
                raise ValueError(
                    f"cannot reset parameter {k!r} on a live Booster "
                    "(requires dataset/grower reconstruction)")
            setattr(self._model.config, canon,
                    _coerce(canon, _PARAMS[canon][0], v))
            # the saved model's parameters section serializes raw_params
            self._model.config.raw_params[canon] = v
            self.config.raw_params[canon] = v
            cegb_touched = cegb_touched or canon.startswith("cegb_")
        if cegb_touched:
            if self._model._dist is not None:
                raise ValueError(
                    "CEGB is not supported with distributed learners")
            self._model._cegb_state = self._model._make_cegb(
                self._model.config, self._model.train_set)
        if "learning_rate" in params or "eta" in params \
                or "shrinkage_rate" in params:
            self._model.learning_rate = float(
                self._model.config.learning_rate)
        # the fused-chunk program bakes the learning rate (and sampling
        # config) into its jitted closure — drop it so the next chunk
        # re-traces with the new values
        self._model._fused_cache.clear()
        return self

    # ------------------------------------------------------------------
    def eval_train(self, feval=None) -> List[Tuple]:
        score = self._model.train_score()
        return self._eval_set(getattr(self, "_train_data_name", "training"),
                              score, self._train_metrics,
                              self.train_set, feval)

    def eval_valid(self, feval=None) -> List[Tuple]:
        out = []
        for i, name in enumerate(self._valid_names):
            score = self._model.valid_score(i)
            ds = self._model.valid_sets[i][0]
            out.extend(self._eval_set(name, score, self._valid_metrics[i],
                                      ds, feval))
        return out

    def _eval_set(self, name, score, metrics, dataset, feval) -> List[Tuple]:
        s = score[:, 0] if self._num_tree_per_iteration == 1 else score
        results = []
        for m in metrics:
            for mname, val, hib in m.eval(s):
                results.append((name, mname, val, hib))
        if feval is not None:
            for fe in (feval if isinstance(feval, (list, tuple)) else [feval]):
                r = fe(s, dataset)
                rs = r if isinstance(r, list) else [r]
                for (mname, val, hib) in rs:
                    results.append((name, mname, val, hib))
        return results

    # ------------------------------------------------------------------
    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None, raw_score: bool = False,
                pred_leaf: bool = False, pred_contrib: bool = False,
                pred_early_stop: bool = False, pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0, **kw) -> np.ndarray:
        """Prediction on raw features (gbdt_prediction.cpp:97 inner loop,
        Predictor analog).  ``pred_early_stop``: margin-based early exit
        across trees (prediction_early_stop.cpp:91)."""
        from .dataset import _is_scipy_sparse, _to_numpy_2d
        if isinstance(data, (str, os.PathLike)):
            # predict-from-file (the reference Predictor's text-input
            # path, c_api.cpp LGBM_BoosterPredictForFile): CSV/TSV/
            # LibSVM sniffed by the loader
            from .data_io import load_text
            data, _ = load_text(str(data))
        # reference contract (c_api predict + basic.py): the feature-count
        # mismatch only raises when predict_disable_shape_check is false
        # (config, or a predict-time override), and the error tells the
        # user about the param
        disable_shape_check = bool(kw.get(
            "predict_disable_shape_check",
            self.config.predict_disable_shape_check))
        if hasattr(data, "shape") and len(getattr(data, "shape", ())) == 2 \
                and data.shape[1] != self._max_feature_idx + 1 \
                and not disable_shape_check:
            # checked BEFORE the chunked-sparse recursion and without a
            # truthiness guard (a 1-feature model has _max_feature_idx
            # == 0 — falsy, but the check must still fire)
            from .basic import LightGBMError
            raise LightGBMError(
                f"The number of features in data ({data.shape[1]}) is "
                f"not the same as it was in training data "
                f"({self._max_feature_idx + 1}).\n"
                "You can set ``predict_disable_shape_check=true`` to "
                "discard this error, but please be aware what you are "
                "doing.")
        if _is_scipy_sparse(data) and data.shape[0] > 65536:
            # CSR prediction (LGBM_BoosterPredictForCSR analog): densify in
            # row chunks so peak memory stays bounded.
            csr = data.tocsr()
            chunks = [self.predict(csr[i:i + 65536],
                                   start_iteration=start_iteration,
                                   num_iteration=num_iteration,
                                   raw_score=raw_score, pred_leaf=pred_leaf,
                                   pred_contrib=pred_contrib,
                                   pred_early_stop=pred_early_stop,
                                   pred_early_stop_freq=pred_early_stop_freq,
                                   pred_early_stop_margin=pred_early_stop_margin,
                                   **kw)
                      for i in range(0, data.shape[0], 65536)]
            return np.concatenate(chunks, axis=0)
        x, _, _ = _to_numpy_2d(data)
        if x.shape[1] != self._max_feature_idx + 1:
            if not disable_shape_check:
                from .basic import LightGBMError
                raise LightGBMError(
                    f"The number of features in data ({x.shape[1]}) is not "
                    f"the same as it was in training data "
                    f"({self._max_feature_idx + 1}).\n"
                    "You can set ``predict_disable_shape_check=true`` to "
                    "discard this error, but please be aware what you are "
                    "doing.")
            # shape check disabled: the reference Predictor copies each
            # row into a ZERO-initialized num_feature buffer, so a
            # missing tail of features compares as 0.0 (a regular value
            # under the default zero_as_missing=false) — zero-fill, not
            # NaN; extra columns are ignored (trees only read trained
            # feature ids)
            nf_model = self._max_feature_idx + 1
            if x.shape[1] < nf_model:
                x = np.concatenate(
                    [x, np.zeros((len(x), nf_model - x.shape[1]),
                                 dtype=x.dtype)], axis=1)
            else:
                x = x[:, :nf_model]
        n = len(x)
        k = self._num_tree_per_iteration
        start_iteration = max(0, start_iteration)
        if num_iteration is None:
            # only an OMITTED num_iteration defaults to the best
            # iteration, and only from the start; an explicit <= 0 means
            # all trees (basic.py predict contract: None -> best, the C
            # side treats non-positive as unbounded)
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0
                             and start_iteration <= 0 else
                             len(self.trees) // k)
        elif num_iteration <= 0:
            num_iteration = len(self.trees) // k
        t0, t1 = start_iteration * k, min((start_iteration + num_iteration) * k,
                                          len(self.trees))
        if n == 0 and not pred_contrib:
            # zero-row input: the empty result of the correct shape and
            # dtype, with NO device work (tracing a zero-row program per
            # batch shape is pure waste) — consistent with the
            # predict_disable_shape_check contract: the feature-count
            # check above already ran
            if pred_leaf:
                return np.zeros((0, t1 - t0), np.int32)
            if not raw_score and self.objective is not None:
                # converted output rides through f32 (convert_output)
                return np.zeros((0, k) if k > 1 else (0,), np.float32)
            return np.zeros((0, k) if k > 1 else (0,), np.float64)
        # bucketed engine path (serve/engine.py): device traversal under
        # a power-of-two-bucket compile cache; leaf routing and score
        # accumulation are byte-identical to the host walk below
        eng = self.predict_engine(n) if not pred_contrib \
            and not pred_early_stop else None
        if eng is not None:
            leaves = eng.leaf_ids(x)
            if pred_leaf:
                return np.ascontiguousarray(leaves[:, t0:t1])
            score = eng.raw_scores(x, t0, t1, leaves=leaves)
            return _finalize_score(score, k, self.objective,
                                   self._average_output, t0, t1,
                                   raw_score)
        if pred_leaf:
            out = np.zeros((n, t1 - t0), np.int32)
            for i, ti in enumerate(range(t0, t1)):
                out[:, i] = self.trees[ti].predict_leaf(x)
            return out
        if pred_contrib:
            from .shap import predict_contrib
            return predict_contrib(self, x, t0, t1)

        score = np.zeros((n, k))
        active = np.ones(n, bool) if pred_early_stop else None
        for it, ti in enumerate(range(t0, t1)):
            if active is not None and not active.any():
                break
            rows = active if active is not None else slice(None)
            score[rows, ti % k] += (self.tree_weights[ti]
                                    * self.trees[ti].predict(
                                        x[rows] if active is not None else x))
            if active is not None and ti % k == k - 1 \
                    and (it // k + 1) % pred_early_stop_freq == 0:
                if k == 1:
                    margin = np.abs(score[:, 0])
                else:
                    part = np.partition(score, -2, axis=1)
                    margin = part[:, -1] - part[:, -2]
                active &= margin < pred_early_stop_margin
        return _finalize_score(score, k, self.objective,
                               self._average_output, t0, t1, raw_score)

    # ------------------------------------------------------------------
    def to_c_code(self, num_iteration: Optional[int] = None) -> str:
        """Standalone C source for this model (GBDT::ModelToIfElse,
        gbdt_model_text.cpp:124 analog; CLI ``task=convert_model``)."""
        from .codegen import model_to_c
        return model_to_c(self, num_iteration=num_iteration)

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        """FeatureImportance (gbdt.cpp / boosting.h:270)."""
        nf = self._max_feature_idx + 1
        imp = np.zeros(nf)
        trees = self.trees if iteration is None else \
            self.trees[:iteration * self._num_tree_per_iteration]
        for t in trees:
            for i in range(t.num_nodes()):
                if importance_type == "split":
                    imp[t.split_feature[i]] += 1
                else:
                    imp[t.split_feature[i]] += t.split_gain[i]
        return imp

    # ------------------------------------------------------------------
    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        """SaveModelToString (gbdt_model_text.cpp:311)."""
        cfg = getattr(self, "config", None)
        buf = io.StringIO()
        buf.write("tree\n")
        buf.write("version=v3\n")
        buf.write(f"num_class={self._num_class}\n")
        buf.write(f"num_tree_per_iteration={self._num_tree_per_iteration}\n")
        buf.write("label_index=0\n")
        buf.write(f"max_feature_idx={self._max_feature_idx}\n")
        obj_str = _objective_to_string(cfg) if cfg else getattr(
            self, "_objective_str", "regression")
        buf.write(f"objective={obj_str}\n")
        if self._average_output:
            buf.write("average_output\n")
        names = self.feature_names or [f"Column_{i}"
                                       for i in range(self._max_feature_idx + 1)]
        buf.write("feature_names=" + " ".join(names) + "\n")
        buf.write("feature_infos=" + " ".join(self._feature_infos()) + "\n")

        k = self._num_tree_per_iteration
        t0 = start_iteration * k
        t1 = len(self.trees) if num_iteration is None else \
            min(t0 + num_iteration * k, len(self.trees))
        blocks = []
        for i, ti in enumerate(range(t0, t1)):
            t = self.trees[ti]
            w = self.tree_weights[ti] if ti < len(self.tree_weights) else 1.0
            if w != 1.0:
                import copy
                t = copy.deepcopy(t)
                t.leaf_value *= w
                t.internal_value *= w
            blocks.append(t.to_string(i) + "\n")
        sizes = [len(b.encode()) for b in blocks]
        buf.write("tree_sizes=" + " ".join(str(s) for s in sizes) + "\n\n")
        for b in blocks:
            buf.write(b)
        buf.write("end of trees\n\n")
        buf.write("feature_importances:\n")
        # gains summed over the trees WRITTEN above ([t0:t1], like the
        # reference's FeatureImportance over the saved range) and rounded
        # through the same %g the tree blocks print: the importance
        # section stays consistent with THIS file's trees, so
        # save -> load -> save is byte-stable (subset saves included) and
        # a crash+resume run (whose leading trees were parsed from a
        # snapshot) sums exactly the gains a straight run's text records
        imp = np.zeros(self._max_feature_idx + 1)
        for t in self.trees[t0:t1]:
            for i in range(t.num_nodes()):
                imp[t.split_feature[i]] += float(f"{t.split_gain[i]:g}")
        order = np.argsort(-imp)
        for fi in order:
            if imp[fi] > 0:
                buf.write(f"{names[fi]}={imp[fi]:g}\n")
        buf.write("\nparameters:\n")
        if cfg is not None:
            for key, val in sorted(cfg.raw_params.items()):
                buf.write(f"[{key}: {val}]\n")
        buf.write("end of parameters\n\n")
        buf.write("pandas_categorical:null\n")
        return buf.getvalue()

    def _feature_infos(self) -> List[str]:
        infos = []
        ds = self.train_set
        if ds is None or ds.bin_mappers is None:
            return ["none"] * (self._max_feature_idx + 1)
        for f in range(ds.num_total_features):
            m = ds.bin_mappers[f]
            if m.is_trivial:
                infos.append("none")
            elif m.bin_type.name == "CATEGORICAL":
                infos.append(":".join(str(int(c)) for c in m.categories))
            else:
                ub = m.bin_upper_bound
                finite = ub[np.isfinite(ub)]
                lo = float(finite[0]) if len(finite) else 0.0
                hi = float(finite[-1]) if len(finite) else 0.0
                infos.append(f"[{lo:g}:{hi:g}]")
        return infos

    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        """Write the model text atomically (temp file + ``os.replace``,
        utils/resilience.py): a crash mid-save can never leave a
        truncated model — the reference writes in place (gbdt_model_text
        SaveModelToFile), which is exactly how the round-5 outage could
        have corrupted its only snapshot."""
        from .utils.resilience import atomic_write
        atomic_write(filename,
                     self.model_to_string(num_iteration, start_iteration))
        return self

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   object_hook=None) -> Dict[str, Any]:
        """JSON model dump (GBDT::DumpModel, gbdt_model_text.cpp:21).
        ``object_hook`` is applied to every JSON object exactly like the
        reference (basic.py dump_model json.loads object_hook)."""
        k = self._num_tree_per_iteration
        t0 = start_iteration * k
        t1 = len(self.trees) if num_iteration is None else \
            min(t0 + num_iteration * k, len(self.trees))
        names = self.feature_names or [f"Column_{i}"
                                       for i in range(self._max_feature_idx + 1)]

        def node_json(t: Tree, node: int) -> Dict[str, Any]:
            if node < 0:
                leaf = ~node
                return {
                    "leaf_index": int(leaf),
                    "leaf_value": float(t.leaf_value[leaf]),
                    "leaf_weight": float(t.leaf_weight[leaf]),
                    "leaf_count": int(t.leaf_count[leaf]),
                }
            is_cat = bool(t.decision_type[node] & 1)
            return {
                "split_index": int(node),
                "split_feature": int(t.split_feature[node]),
                "split_gain": float(t.split_gain[node]),
                "threshold": float(t.threshold[node]),
                "decision_type": "==" if is_cat else "<=",
                "default_left": bool(t.decision_type[node] & 2),
                "missing_type": ["None", "Zero", "NaN"][
                    (t.decision_type[node] >> 2) & 3],
                "internal_value": float(t.internal_value[node]),
                "internal_weight": float(t.internal_weight[node]),
                "internal_count": int(t.internal_count[node]),
                "left_child": node_json(t, t.left_child[node]),
                "right_child": node_json(t, t.right_child[node]),
            }

        trees = []
        for i, ti in enumerate(range(t0, t1)):
            t = self.trees[ti]
            trees.append({
                "tree_index": i,
                "num_leaves": int(t.num_leaves),
                "num_cat": int(t.num_cat),
                "shrinkage": float(t.shrinkage),
                "tree_structure": node_json(t, 0 if t.num_leaves > 1 else -1),
            })
        out = {
            "name": "tree",
            "version": "v3",
            "num_class": self._num_class,
            "num_tree_per_iteration": self._num_tree_per_iteration,
            "label_index": 0,
            "max_feature_idx": self._max_feature_idx,
            "objective": getattr(self, "_objective_str", None) or
                (_objective_to_string(self.config) if hasattr(self, "config")
                 else "regression"),
            "average_output": self._average_output,
            "feature_names": names,
            "feature_importances": {
                names[f]: float(v)
                for f, v in enumerate(self.feature_importance("gain")) if v > 0},
            "tree_info": trees,
        }
        if object_hook is not None:
            import json as _json
            out = _json.loads(_json.dumps(out), object_hook=object_hook)
        return out

    # -- python-package convenience surface (basic.py parity) ----------
    def attr(self, key: str):
        """In-memory model attribute (basic.py Booster.attr)."""
        return getattr(self, "_attrs", {}).get(key)

    def set_attr(self, **kwargs) -> "Booster":
        """Set/unset (value None) model attributes (basic.py set_attr)."""
        attrs = getattr(self, "_attrs", None)
        if attrs is None:
            attrs = self._attrs = {}
        for k, v in kwargs.items():
            if v is None:
                attrs.pop(k, None)
            else:
                attrs[k] = str(v)
        return self

    def feature_name(self) -> List[str]:
        return list(self.feature_names)

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        """LGBM_BoosterShuffleModels analog (basic.py shuffle_models)."""
        self._shuffle_models(start_iteration, end_iteration)
        return self

    def _bounds(self):
        """(lower, upper) summed per tree.  The reference folds shrinkage
        into leaf values so GetLowerBoundValue sums raw leaf extrema; this
        framework applies tree_weights at predict time (DART/RF), so the
        extrema must be scaled by the same weights here."""
        weights = list(self.tree_weights) if self.tree_weights else []
        lo = hi = 0.0
        for ti, t in enumerate(self.trees):
            w = float(weights[ti]) if ti < len(weights) else 1.0
            mn = float(np.min(t.leaf_value[:max(t.num_leaves, 1)])) * w
            mx = float(np.max(t.leaf_value[:max(t.num_leaves, 1)])) * w
            lo += min(mn, mx)
            hi += max(mn, mx)
        return lo, hi

    def lower_bound(self) -> float:
        """Weighted sum of per-tree minimum leaf values
        (GetLowerBoundValue)."""
        return self._bounds()[0]

    def upper_bound(self) -> float:
        """Weighted sum of per-tree maximum leaf values
        (GetUpperBoundValue)."""
        return self._bounds()[1]

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        return float(self.trees[tree_id].leaf_value[leaf_id])

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def get_split_value_histogram(self, feature, bins=None):
        """Histogram of a feature's split thresholds across the model
        (basic.py get_split_value_histogram)."""
        if isinstance(feature, str):
            feature = self.feature_names.index(feature)
        vals = [float(t.threshold[n]) for t in self.trees
                for n in range(t.num_nodes())
                if int(t.split_feature[n]) == int(feature)]
        vals = np.asarray(vals, np.float64)
        if bins is None:
            bins = max(min(len(vals), 32), 1)
        return np.histogram(vals, bins=bins)

    def trees_to_dataframe(self):
        """One row per node/leaf across the model
        (basic.py trees_to_dataframe); requires pandas."""
        import pandas as pd
        rows = []
        for ti, t in enumerate(self.trees):
            parents = {}
            for n in range(t.num_nodes()):
                for c in (t.left_child[n], t.right_child[n]):
                    parents[int(c)] = f"{ti}-S{n}"
            # 1-based depth by walk from the root (basic.py column)
            depth = {0: 1} if t.num_nodes() else {}
            stack = [0] if t.num_nodes() else [~0]
            if not t.num_nodes():
                depth[~0] = 1
            while stack:
                n = stack.pop()
                if n < 0:
                    continue
                for c in (int(t.left_child[n]), int(t.right_child[n])):
                    depth[c] = depth[n] + 1
                    if c >= 0:
                        stack.append(c)
            for n in range(t.num_nodes()):
                rows.append({
                    "tree_index": ti,
                    "node_depth": depth.get(n),
                    "node_index": f"{ti}-S{n}",
                    "left_child": f"{ti}-S{t.left_child[n]}"
                    if t.left_child[n] >= 0 else f"{ti}-L{~t.left_child[n]}",
                    "right_child": f"{ti}-S{t.right_child[n]}"
                    if t.right_child[n] >= 0 else f"{ti}-L{~t.right_child[n]}",
                    "parent_index": parents.get(n),
                    "split_feature": (self.feature_names[
                        int(t.split_feature[n])]
                        if self.feature_names else int(t.split_feature[n])),
                    "split_gain": float(t.split_gain[n]),
                    "threshold": float(t.threshold[n]),
                    "decision_type": "==" if (t.decision_type[n] & 1)
                    else "<=",
                    "missing_direction": "left"
                    if (t.decision_type[n] & 2) else "right",
                    "missing_type": ["None", "Zero", "NaN"][
                        (int(t.decision_type[n]) >> 2) & 3],
                    "value": float(t.internal_value[n]),
                    "weight": float(t.internal_weight[n]),
                    "count": int(t.internal_count[n]),
                })
            for leaf in range(t.num_leaves):
                rows.append({
                    "tree_index": ti,
                    "node_depth": depth.get(~leaf, 1),
                    "node_index": f"{ti}-L{leaf}",
                    "left_child": None, "right_child": None,
                    "parent_index": parents.get(~leaf),
                    "split_feature": None, "split_gain": None,
                    "threshold": None, "decision_type": None,
                    "missing_direction": None, "missing_type": None,
                    "value": float(t.leaf_value[leaf]),
                    # a stump records no weight/count (the reference's
                    # single-leaf tree_structure carries only the value)
                    "weight": float(t.leaf_weight[leaf])
                    if t.num_nodes() else None,
                    "count": int(t.leaf_count[leaf])
                    if t.num_nodes() else None,
                })
        return pd.DataFrame(rows)

    def eval(self, data: Dataset, name: str, feval=None) -> List[Tuple]:
        """Evaluate on an arbitrary dataset (basic.py Booster.eval)."""
        # grab the raw values BEFORE construct() (which may free them
        # under free_raw_data=True); predict() accepts dense or sparse
        raw = data.get_data()
        data.construct(self.config)
        score = np.asarray(self.predict(raw, raw_score=True))
        score = score.reshape(data.num_data, -1)
        metrics = self._make_metrics(data.metadata, data.num_data)
        return self._eval_set(name, score, metrics, data, feval)

    def refit(self, data, label, decay_rate: float = 0.9, **kw) -> "Booster":
        """Refit existing tree structures on new data
        (Booster.refit, basic.py / GBDT::RefitTree gbdt.cpp:287)."""
        import copy as _copy
        from .cli import refit as _refit
        from .dataset import _to_numpy_2d
        x, _, _ = _to_numpy_2d(data)
        new_booster = Booster(model_str=self.model_to_string())
        cfg = new_booster.config
        cfg.refit_decay_rate = decay_rate
        return _refit(new_booster, x, np.asarray(label, np.float32), cfg)

    def refit_with_leaves(self, leaf_preds: np.ndarray) -> "Booster":
        """GBDT::RefitTree with GIVEN per-tree leaf assignments
        (LGBM_BoosterRefit, c_api.h:578; gbdt.cpp:287-323): re-fit every
        tree's leaf values from the training labels' gradients at the
        evolving score, blending with refit_decay_rate.  ``leaf_preds``
        is [num_data, num_trees] (the pred_leaf layout)."""
        if self.train_set is None:
            raise ValueError("refit_with_leaves needs a booster with "
                             "training data (LGBM_BoosterCreate)")
        from .cli import refit_leaf_values
        leaf_preds = np.asarray(leaf_preds, np.int32)
        y = np.asarray(self.train_set.metadata.label, np.float32)
        refit_leaf_values(self, leaf_preds, y, self.config)
        # sync the model's cached state with the new leaf values (the
        # reference RefitTree runs train_score_updater_->AddScore per
        # tree, gbdt.cpp:320): device copies + the training score, so a
        # following UpdateOneIter/GetPredict sees the refit model
        m = getattr(self, "_model", None)
        if m is not None:
            import jax.numpy as jnp
            k = self._num_tree_per_iteration
            score = np.zeros((leaf_preds.shape[0], k), np.float64)
            for ti, t in enumerate(self.trees):
                if ti < len(m.device_trees):
                    dt = m.device_trees[ti]
                    lv = np.zeros(np.asarray(dt.leaf_value).shape[0],
                                  np.float32)
                    lv[:t.num_leaves] = t.leaf_value[:t.num_leaves]
                    dt.leaf_value = jnp.asarray(lv)
                w = m.tree_weights[ti] if ti < len(m.tree_weights) else 1.0
                score[:, ti % k] += w * t.leaf_value[leaf_preds[:, ti]]
            m.score = jnp.asarray(score, jnp.float32)
        self._drop_predict_cache()   # leaf values changed in place
        return self

    def _merge_from(self, other: "Booster") -> None:
        """LGBM_BoosterMerge (c_api.h:522): insert other's trees at the
        FRONT of this booster, self's after — GBDT::MergeFrom
        (gbdt.h:63-80) pushes the other booster's models first, so
        order-sensitive consumers (pred_leaf columns, iteration slicing,
        tree indices, saved tree order) must see other-first here too."""
        if other._num_tree_per_iteration != self._num_tree_per_iteration:
            raise ValueError("cannot merge boosters with different "
                             "num_tree_per_iteration")
        import copy as _copy
        new_trees = [_copy.deepcopy(t) for t in other.trees]
        new_weights = (list(other.tree_weights) if other.tree_weights
                       else [1.0] * len(new_trees))
        if self._model is not None:
            m = self._model
            m.models[:0] = new_trees
            m.tree_weights[:0] = new_weights
            # device_trees must stay aligned to the TAIL of models
            # (models/gbdt.py add_valid_set: the first
            # len(models)-len(device_trees) trees replay host-side).
            # Inserting at the front keeps self's device tail intact;
            # other's device copies can only be prepended when BOTH
            # sides have full device coverage (otherwise a gap would
            # break the tail invariant).
            other_dev = (other._model.device_trees
                         if getattr(other, "_model", None) is not None
                         else [])
            if (len(other_dev) == len(new_trees)
                    and len(m.device_trees)
                    == len(m.models) - len(new_trees)):
                m.device_trees[:0] = other_dev
            m.iter_ += len(new_trees) // self._num_tree_per_iteration
            self._sync_trees()
        else:
            self.trees[:0] = new_trees
            self.tree_weights[:0] = new_weights
            self._drop_predict_cache()

    def _shuffle_models(self, start_iter: int, end_iter: int) -> None:
        """LGBM_BoosterShuffleModels (c_api.h:512; GBDT::ShuffleModels):
        permute whole iterations in [start_iter, end_iter) (<=0 end =
        all) with the reference's fixed Random(17) swap sequence."""
        k = self._num_tree_per_iteration
        trees = self.trees
        n_iter = len(trees) // k
        end_iter = n_iter if end_iter <= 0 else min(end_iter, n_iter)
        start_iter = max(0, start_iter)
        if end_iter - start_iter < 2:
            return
        # reference-exact permutation: GBDT::ShuffleModels (gbdt.h:82-105)
        # runs a partial Fisher-Yates with its own LCG seeded at 17
        # (Random::NextShort, utils/random.h: x = 214013*x + 2531011,
        # take bits 16..30) — reproduce the identical swap sequence so
        # LGBM_BoosterShuffleModels matches the reference ABI bit-for-bit
        lcg = 17

        def _next_short(lo: int, hi: int) -> int:
            nonlocal lcg
            lcg = (214013 * lcg + 2531011) & 0xFFFFFFFF
            return ((lcg >> 16) & 0x7FFF) % (hi - lo) + lo

        indices = list(range(n_iter))
        for i in range(start_iter, end_iter - 1):
            j = _next_short(i + 1, end_iter)
            indices[i], indices[j] = indices[j], indices[i]
        perm = [indices[i] for i in range(start_iter, end_iter)]

        def _permute(seq):
            """Apply the same iteration-block permutation to any list
            position-paired with the trees (weights, device trees)."""
            if len(seq) != len(trees):
                return seq             # not paired 1:1 — leave untouched
            blocks = [seq[i * k:(i + 1) * k] for i in range(n_iter)]
            shuffled = (blocks[:start_iter]
                        + [blocks[i] for i in perm]
                        + blocks[end_iter:])
            return [t for b in shuffled for t in b]

        new_trees = _permute(trees)
        if self._model is not None:
            m = self._model
            m.tree_weights[:] = _permute(list(m.tree_weights))
            if len(m.device_trees) == len(trees):
                m.device_trees[:] = _permute(list(m.device_trees))
            elif m.device_trees:
                # partial device coverage cannot stay tail-aligned under
                # a permutation of all models — drop the device copies
                # and let consumers (add_valid_set) replay host-side
                m.device_trees.clear()
            m.models[:] = new_trees
            self._sync_trees()
        else:
            self.tree_weights[:] = _permute(list(self.tree_weights))
            self.trees[:] = new_trees
            self._drop_predict_cache()

    def reset_training_data(self, train_set) -> "Booster":
        """LGBM_BoosterResetTrainingData (c_api.h:540): keep the model,
        continue training on a different dataset.  The training score is
        rebuilt by predicting the new data with the current model."""
        from .models import create_boosting
        from .objectives import create_objective
        import jax.numpy as jnp
        old_models = self._model.models if self._model is not None \
            else list(self.trees)
        old_weights = self._model.tree_weights if self._model is not None \
            else list(self.tree_weights)
        old_iter = (self._model.iter_ if self._model is not None
                    else len(old_models) // self._num_tree_per_iteration)
        cfg = self.config
        if not train_set._constructed and train_set.params:
            # dataset params are the binning base (see __init__); the
            # booster's training params override
            from .config import canonical_params
            cfg = Config({**canonical_params(train_set.params),
                          **canonical_params(self.config.raw_params)})
        new_train = train_set.construct(cfg)
        if old_models and new_train.raw_data is None:
            # without raw values the existing ensemble cannot be scored
            # on the new data — continuing would silently train as if
            # the model predicted zero everywhere (same guard as
            # add_valid_set for the free_raw_data=True case); checked
            # BEFORE any state is replaced so a caught error leaves the
            # booster usable
            raise ValueError(
                "reset_training_data on a non-empty booster needs the new "
                "dataset's raw values to rebuild the training score; "
                "construct it with free_raw_data=False")
        self.train_set = new_train
        self._model = create_boosting(self.config, self.train_set,
                                      create_objective(self.config))
        m = self._model
        m.models = list(old_models)
        m.tree_weights = list(old_weights)
        m.iter_ = old_iter
        if old_models and self.train_set.raw_data is not None:
            raw = np.asarray(self.train_set.raw_data, np.float64)
            score = np.zeros((len(raw), self._num_tree_per_iteration),
                             np.float64)
            for ti, t in enumerate(old_models):
                kk = ti % self._num_tree_per_iteration
                w = old_weights[ti] if ti < len(old_weights) else 1.0
                score[:, kk] += w * t.predict(raw)
            m.score = jnp.asarray(score, jnp.float32)
        self._sync_trees()
        return self

    # ------------------------------------------------------------------
    def _load_model_string(self, s: str) -> None:
        """LoadModelFromString (gbdt_model_text.cpp:421)."""
        if "num_class=" not in s:
            raise ValueError("input is not a lightgbm_tpu model "
                             "(missing header)")
        header, _, rest = s.partition("\nTree=")
        kv: Dict[str, str] = {}
        for line in header.splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
            elif line.strip() == "average_output":
                self._average_output = True
        self._num_class = int(kv.get("num_class", "1"))
        self._num_tree_per_iteration = int(kv.get("num_tree_per_iteration", "1"))
        self._max_feature_idx = int(kv.get("max_feature_idx", "0"))
        self._objective_str = kv.get("objective", "regression")
        self.feature_names = kv.get("feature_names", "").split(" ") \
            if kv.get("feature_names") else []
        obj_kv = _objective_from_string(self._objective_str)
        params = {"objective": obj_kv.pop("objective", "regression")}
        params.update(obj_kv)
        self.config = Config(params)
        # loaded boosters predict through jitted paths too (bucketed
        # engine / serve): same cache bring-up as the training path
        from .utils.compile_cache import maybe_enable_from_config
        maybe_enable_from_config(self.config)
        self.objective = create_objective(self.config)

        body = "Tree=" + rest
        tree_blocks = body.split("\nend of trees")[0]
        self.trees = []
        for block in tree_blocks.split("Tree="):
            block = block.strip()
            if not block:
                continue
            self.trees.append(Tree.from_string("Tree=" + block))
        self.tree_weights = [1.0] * len(self.trees)
        self.best_iteration = -1

    @classmethod
    def model_from_string(cls, model_str: str) -> "Booster":
        return cls(model_str=model_str)
