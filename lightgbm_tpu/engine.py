"""Training entry points: ``train`` and ``cv``.

Analog of the reference python-package engine
(/root/reference/python-package/lightgbm/engine.py:25 ``train``, :375 ``cv``):
parameter normalization, valid-set wiring, per-iteration callbacks, early
stopping via EarlyStopException (engine.py:252), and CVBooster aggregation.
"""

from __future__ import annotations

import collections
import copy
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import callback as callback_mod
from .booster import Booster
from .callback import CallbackEnv, EarlyStopException
from .config import Config
from .dataset import Dataset


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj: Optional[Callable] = None,
          feval: Optional[Callable] = None,
          init_model: Optional[Union[str, Booster]] = None,
          feature_name="auto", categorical_feature="auto",
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None) -> Booster:
    """Train a gradient-boosted model (engine.py:25 analog).

    ``fobj`` sits in the reference's positional slot — between
    ``valid_names`` and ``feval`` (v3.3.2 engine.py:25), matching ``cv``
    — so reference-style positional calls bind the custom objective and
    custom metric to the right parameters.

    ``resume=true`` in ``params`` auto-resumes from the newest VALID
    snapshot of ``output_model`` (manifest params-signature + data
    fingerprint must match, snapshot.py) through this function's
    init_model path; train-straight and crash-then-resume produce
    byte-identical model text (docs/Fault-Tolerance.md).

    Under ``integrity_policy=rewind`` (lightgbm_tpu/integrity.py) a
    sticky silent-data-corruption failure rewinds here: training
    re-enters with ``resume=true``, which lands on the newest
    integrity-VERIFIED snapshot (``find_latest_snapshot`` prefers the
    stamp) and replays byte-identically — up to
    ``integrity.MAX_REWINDS`` times before the failure propagates."""
    from .integrity import MAX_REWINDS, IntegrityFailure
    rewinds = 0
    while True:
        try:
            return _train_impl(params, train_set, num_boost_round,
                               valid_sets, valid_names, fobj, feval,
                               init_model, feature_name,
                               categorical_feature,
                               keep_training_booster, callbacks)
        except IntegrityFailure as sdc:
            from .config import canonical_params
            cp = canonical_params(dict(params or {}))
            policy = str(cp.get("integrity_policy", "raise"))
            if policy != "rewind" or init_model is not None \
                    or rewinds >= MAX_REWINDS:
                # raise/quarantine surface the classified failure (the
                # elastic ladder catches kind "sdc" and re-enters with
                # a quarantined mesh); an explicit init_model run has
                # no self-owned snapshot history to rewind into
                raise
            rewinds += 1
            from .integrity import _metrics as _int_metrics
            _int_metrics().counter("integrity.rewinds").inc()
            from .utils.log import Log
            Log.warning(
                f"integrity: sticky SDC at iteration {sdc.iteration}; "
                "rewinding to the newest integrity-verified snapshot "
                f"(attempt {rewinds}/{MAX_REWINDS})")
            params = dict(params or {})
            params["resume"] = True


def _train_impl(params: Dict[str, Any], train_set: Dataset,
                num_boost_round: int,
                valid_sets, valid_names, fobj, feval, init_model,
                feature_name, categorical_feature,
                keep_training_booster, callbacks) -> Booster:
    """One training attempt (the body of :func:`train`; the wrapper
    owns only the integrity-rewind re-entry loop)."""
    params = dict(params or {})
    # resume is a run-control switch, not a model hyperparameter: strip
    # it (and its aliases) from the params that reach the Booster so the
    # saved parameters section is identical between a straight run and a
    # crash+resume run
    from .config import _ALIASES, _coerce
    resume_req = False
    for k in list(params):
        if _ALIASES.get(k, k) == "resume":
            resume_req = bool(_coerce("resume", bool, params.pop(k)))
    cfg = Config(params)
    cfg.resume = resume_req
    # persistent-compile-cache bring-up before any jax work (binning /
    # init-score prediction may already trace): warm-starts every compile
    # of this process from the on-disk cache (docs/Compile-Cache.md)
    from .utils.compile_cache import maybe_enable_from_config
    maybe_enable_from_config(cfg)
    from .config import canonical_params
    if "num_iterations" in canonical_params(params):
        # any num_iterations alias in params overrides the keyword
        # unconditionally (reference train pops the alias and wins)
        num_boost_round = cfg.num_iterations
    # ...and the effective round count is written back so the saved
    # model's parameters section records it (reference train sets
    # params["num_iterations"] = num_boost_round)
    params["num_iterations"] = num_boost_round
    if valid_sets is not None and not isinstance(valid_sets, (list, tuple)):
        valid_sets = [valid_sets]       # reference accepts a bare Dataset
    if isinstance(valid_names, str):
        valid_names = [valid_names]
    if feature_name != "auto" and not train_set._constructed:
        train_set.set_feature_name(feature_name)
    if categorical_feature != "auto" and not train_set._constructed:
        train_set.set_categorical_feature(categorical_feature)

    # continued training: init_model predictions become the init score
    # (application.cpp:88-94 input_model pattern)
    prev_booster = None
    resume_start = 0
    snap_sig = None
    if cfg.snapshot_freq > 0 or resume_req:
        from .snapshot import params_signature
        snap_sig = params_signature(params)
    if init_model is not None:
        prev_booster = (Booster(model_file=init_model)
                        if isinstance(init_model, str) else init_model)
        raw = prev_booster.predict(_dataset_raw(train_set), raw_score=True)
        train_set.set_init_score(np.asarray(raw, np.float64))
    elif resume_req:
        from .snapshot import find_latest_snapshot
        from .utils.log import Log
        found = find_latest_snapshot(cfg.output_model, snap_sig, train_set)
        if found is not None:
            resume_start, snap_path, snap_score = found
            try:
                prev_booster = Booster(model_file=snap_path)
            except FileNotFoundError:
                # the snapshot the finder located was pruned before the
                # open (a concurrent writer's prune_snapshots —
                # find->open TOCTOU): re-scan ONCE instead of failing
                # the bring-up; an older valid snapshot still resumes
                Log.warning(f"snapshot {snap_path} vanished between "
                            "lookup and load; re-scanning once")
                found = find_latest_snapshot(cfg.output_model, snap_sig,
                                             train_set)
                if found is not None:
                    resume_start, snap_path, snap_score = found
                    prev_booster = Booster(model_file=snap_path)
                else:
                    resume_start = 0
        if found is None:
            Log.info("resume=true but no valid snapshot found for "
                     f"{cfg.output_model!r}; training from scratch")
        else:
            # the saved f32 training score IS the device state at the
            # snapshot — feeding it back through the init_model path
            # continues training bit-exactly where the crash hit (a
            # re-prediction of the snapshot model would differ in the
            # last ulp and change the trees grown after the resume)
            row_range = getattr(train_set, "elastic_row_range", None)
            if row_range is not None:
                # elastic multi-process resume: the snapshot carries
                # the GLOBAL score (GBDTModel.snapshot_state); this
                # process feeds back only its own shard's rows
                snap_score = snap_score[row_range[0]:row_range[1]]
            train_set.set_init_score(np.asarray(snap_score, np.float64))
            Log.info(f"auto-resume: continuing from {snap_path} "
                     f"(iteration {resume_start})")

    booster = Booster(params=params, train_set=train_set)
    if resume_start and booster._model is not None:
        # align iteration-keyed RNG streams (bagging epochs, GOSS keys,
        # feature-fraction draws) with the straight run
        booster._model.set_resume_state(resume_start)
    # early stopping reports best_iteration ABSOLUTE over the final
    # merged forest: with an explicit init_model the loop index starts
    # at 0 while the forest still carries the previous model's trees
    # (predict/save slicing at a run-relative index would silently drop
    # the continuation's best trees); a RESUMED run's loop index is
    # already absolute (it starts at resume_start == the snapshot's
    # iterations), so the two offsets cancel there
    best_iter_offset = 0
    if prev_booster is not None:
        k = max(1, booster._num_tree_per_iteration)
        best_iter_offset = len(prev_booster.trees) // k - resume_start
    train_eval_name = None
    if valid_sets:
        names = valid_names or [
            "training" if vs is train_set else f"valid_{i}"
            for i, vs in enumerate(valid_sets)]
        for vs, name in zip(valid_sets, names):
            if vs is train_set:
                # reference semantics: the training set in valid_sets
                # means "report training metrics under this name"
                # (engine.py train: name_valid_sets / 'training')
                train_eval_name = name
                booster._train_data_name = name
                continue
            booster.add_valid(vs, name)

    cbs = list(callbacks or [])
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        cbs.append(callback_mod.early_stopping(
            cfg.early_stopping_round, cfg.first_metric_only, cfg.verbosity > 0))
    if cfg.verbosity > 0 and cfg.metric_freq > 0 and \
            not any(getattr(c, "order", 0) == 10 and not
                    getattr(c, "before_iteration", False) for c in cbs):
        pass  # explicit log_evaluation only (sklearn-compatible silence)
    cbs_before = [c for c in cbs if getattr(c, "before_iteration", False)]
    cbs_after = [c for c in cbs if not getattr(c, "before_iteration", False)]
    cbs_before.sort(key=lambda c: getattr(c, "order", 0))
    cbs_after.sort(key=lambda c: getattr(c, "order", 0))

    import time as _time
    t_start = _time.time()

    # fused chunks: when no per-iteration host work is needed (no
    # callbacks, eval, snapshots or custom fobj), run iterations in
    # on-device chunks of ``fused_chunk`` — one host sync per chunk
    # instead of ~5 per iteration (decisive on a tunneled chip; see
    # PROFILE.md).  Any remainder falls through to the per-iter loop.
    start_round = resume_start
    chunk_stopped = False
    chunk = cfg.fused_chunk
    if (chunk > 1 and fobj is None and not cbs
            and not booster._valid_names
            and not cfg.is_provide_training_metric
            and train_eval_name is None
            and cfg.snapshot_freq <= 0 and cfg.verbosity <= 1
            and booster.supports_fused()):
        while num_boost_round - start_round >= chunk and not chunk_stopped:
            chunk_stopped = booster.update_chunk(chunk)
            # current_iteration counts only THIS booster's iterations;
            # a resumed run's global round index carries the offset
            start_round = resume_start + booster.current_iteration

    # super-epochs: the whole-run on-device path — k FULL iterations
    # (growth + score + valid scoring + traced eval + early-stop vote)
    # per device program, ONE host sync each, then the fetched eval
    # block replayed through the REAL callbacks so record_evals /
    # early_stopping / best_iteration are byte-identical per-iteration
    se_plan = None if chunk_stopped else _superepoch_plan(
        cfg, booster, fobj, feval, cbs_before, cbs_after,
        train_eval_name)
    if se_plan is not None:
        base_k, eval_spec, es_spec = se_plan
        from .utils.log import Log
        while not chunk_stopped:
            k_eff = min(base_k, num_boost_round - start_round)
            if cfg.snapshot_freq > 0:
                # clip to the snapshot boundary so periodic snapshots
                # land at EXACTLY the per-iteration cadence
                k_eff = min(k_eff, cfg.snapshot_freq
                            - start_round % cfg.snapshot_freq)
            if k_eff < 2:
                break
            out = booster.update_superepoch(k_eff, start_round,
                                            eval_spec, es_spec)
            done = out["done"]
            if cfg.snapshot_freq > 0 and done == k_eff \
                    and (start_round + done) % cfg.snapshot_freq == 0:
                # per-iteration order is update -> snapshot -> evals ->
                # callbacks, so the boundary snapshot is written BEFORE
                # the replay may raise EarlyStopException
                from .snapshot import write_snapshot
                try:
                    write_snapshot(booster, prev_booster, cfg,
                                   start_round + done, snap_sig,
                                   train_set)
                except Exception as e:
                    Log.warning(f"snapshot at iteration "
                                f"{start_round + done} failed ({e}); "
                                "training continues")
            es_raised = False
            for j in range(done):
                ev_row = [(nm, mn, float(out["evals"][j][e]), hib)
                          for e, (_vi, nm, mn, hib)
                          in enumerate(eval_spec)]
                env = CallbackEnv(model=booster, params=params,
                                  iteration=start_round + j,
                                  begin_iteration=0,
                                  end_iteration=num_boost_round,
                                  evaluation_result_list=ev_row)
                try:
                    for cb in cbs_after:
                        cb(env)
                except EarlyStopException as e:
                    booster.best_iteration = (best_iter_offset
                                              + e.best_iteration + 1)
                    for (name, metric, value, _) in e.best_score:
                        booster.best_score.setdefault(
                            name, {})[metric] = value
                    es_raised = True
                    extra = done - (j + 1)
                    if extra > 0:
                        # defensive: the traced vote and this replay
                        # consume the SAME fetched f32 values, so they
                        # agree on the stop row — heal by slicing the
                        # surplus trees if they ever don't
                        Log.warning(
                            "super-epoch vote overshot the host early "
                            f"stop by {extra} iteration(s); dropping "
                            "surplus trees")
                        booster._model.drop_iterations(extra)
                        booster._sync_trees()
                    break
            if es_raised or out["stump"]:
                chunk_stopped = True
            elif out["stop_row"] is not None:
                # vote tripped but the replay did not raise (defensive
                # mirror of the overshoot case): trust the host, clear
                # the latch, keep training
                Log.warning("super-epoch early-stop vote tripped but "
                            "the host callbacks did not; resuming")
                booster._model.clear_es_stop()
            start_round = resume_start + booster.current_iteration
        if not chunk_stopped and start_round < num_boost_round \
                and eval_spec:
            # remainder rounds run per-iteration but keep the TRACED
            # metric values, so the whole run's record_evals stays
            # bit-identical to a pure super-epoch run
            booster._traced_eval = True
    elif str(cfg.fused_eval).lower() == "true" and feval is None \
            and booster._valid_names \
            and getattr(booster, "_model", None) is not None:
        # fused_eval=true: per-iteration runs evaluate via the traced
        # metric kernels too (ONE fetch per iteration for all metrics)
        # — the reference twin the super-epoch byte-identity tests
        # compare against
        import jax
        from .metrics import traced_metric_fn
        if all(traced_metric_fn(mt.name, cfg) is not None
               for ms in booster._valid_metrics for mt in ms) \
                and all(isinstance(vb, jax.Array) for _, vb, _
                        in booster._model.valid_sets):
            booster._traced_eval = True

    for i in range(start_round, num_boost_round if not chunk_stopped else 0):
        env = CallbackEnv(model=booster, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=None)
        for cb in cbs_before:
            cb(env)
        try:
            stopped = booster.update(fobj=fobj)
        except Exception:
            # flight-recorder trigger (obs/blackbox.py): dump the last
            # K iteration records before the exception propagates —
            # cheap no-op when no recorder is live
            from .obs import blackbox
            blackbox.dump_all("train_exception")
            raise
        if cfg.verbosity > 1:
            from .utils.log import Log
            Log.info(f"{_time.time() - t_start:.6f} seconds elapsed, "
                     f"finished iteration {i + 1}")
        if cfg.snapshot_freq > 0 and (i + 1) % cfg.snapshot_freq == 0:
            # integrity boundary check FIRST, and OUTSIDE the write's
            # skip-and-warn: the manifest's integrity stamp must mean
            # 'verified AT this snapshot', and a sticky boundary
            # mismatch must fail the run (IntegrityFailure), never be
            # swallowed as a failed write
            ib = getattr(getattr(booster, "_model", None),
                         "integrity_boundary_check", None)
            if ib is not None:
                ib()
            # periodic crash-safe snapshot: model + f32 score state +
            # manifest, each written atomically; prunes to snapshot_keep
            # (gbdt.cpp:279-284 snapshot_freq + snapshot.py)
            from .snapshot import write_snapshot
            try:
                write_snapshot(booster, prev_booster, cfg, i + 1,
                               snap_sig, train_set)
            except Exception as e:
                # a full disk (or an injected write failure) must not
                # kill a long training run — skip the snapshot, loudly
                from .utils.log import Log
                Log.warning(f"snapshot at iteration {i + 1} failed "
                            f"({e}); training continues")
        evals = []
        if booster._valid_names or cfg.is_provide_training_metric \
                or train_eval_name is not None:
            if cfg.is_provide_training_metric or train_eval_name is not None:
                evals.extend(booster.eval_train(feval))
            if getattr(booster, "_traced_eval", False) and feval is None:
                evals.extend(booster.eval_valid_traced())
            else:
                evals.extend(booster.eval_valid(feval))
        if evals:
            # flight recorder: fold the train/valid metrics (computed
            # after the iteration record landed) into that record
            bb = getattr(getattr(booster, "_model", None), "_bbox", None)
            if bb is not None:
                bb.annotate_last(evals=[[nm, met, float(v)]
                                        for (nm, met, v, _) in evals])
        env = CallbackEnv(model=booster, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=evals)
        try:
            for cb in cbs_after:
                cb(env)
        except EarlyStopException as e:
            booster.best_iteration = best_iter_offset + e.best_iteration + 1
            for (name, metric, value, _) in e.best_score:
                booster.best_score.setdefault(name, {})[metric] = value
            # roll back to best iteration for prediction default
            break
        if stopped:
            break

    if prev_booster is not None:
        # merge: previous trees come first (continued training model)
        booster.trees = prev_booster.trees + booster.trees
        booster.tree_weights = (prev_booster.tree_weights
                                + booster.tree_weights)
    return booster


def _superepoch_plan(cfg, booster, fobj, feval, cbs_before, cbs_after,
                     train_eval_name):
    """Decide whether the super-epoch trainer (GBDTModel.
    train_superepoch) can drive this run, and with what epoch size.
    Returns ``(base_k, eval_spec, es_spec)`` or None for the
    per-iteration path.  Requirements (docs/Fused-Training.md): the
    fused-path model config, no custom fobj/feval, no training-set
    eval, only replay-safe callbacks, dense device valid sets whose
    metrics all have traced kernels, and at most one early-stopping
    callback in its scalar ``min_delta == 0`` form."""
    if cfg.superepoch == -1:
        return None
    if not (cfg.superepoch > 0 or cfg.fused_chunk > 1):
        return None
    if fobj is not None or feval is not None:
        return None
    if cfg.is_provide_training_metric or train_eval_name is not None:
        return None
    if cfg.verbosity > 1:
        return None       # per-iteration elapsed-time logging
    if cbs_before:
        return None
    if any(not getattr(cb, "_replayable", False) for cb in cbs_after):
        return None
    model = getattr(booster, "_model", None)
    if model is None or not hasattr(model, "train_superepoch"):
        return None
    if not model._fusable_config() or model._faults_active():
        return None
    if getattr(model, "_integrity", None) is not None:
        return None       # integrity layer: per-iteration path only
    import jax
    if str(cfg.fused_eval).lower() == "false" and model.valid_sets:
        return None
    if any(not isinstance(vb, jax.Array)
           for _, vb, _ in model.valid_sets):
        return None       # sparse-binned valid rows: no in-scan walk
    from .metrics import traced_metric_fn
    eval_spec = []
    for vi, name in enumerate(booster._valid_names):
        for mt in booster._valid_metrics[vi]:
            if traced_metric_fn(mt.name, cfg) is None:
                return None
            eval_spec.append((vi, name, mt.name,
                              bool(mt.is_higher_better)))
    eval_spec = tuple(eval_spec)
    es_cbs = [cb for cb in cbs_after
              if getattr(cb, "_es_spec", None) is not None]
    if len(es_cbs) > 1:
        return None
    es_spec = None
    if es_cbs:
        spec = es_cbs[0]._es_spec
        md = spec["min_delta"]
        if isinstance(md, (list, tuple)) or float(md) != 0.0:
            return None
        # which entries the host closure's trip-check actually reaches:
        # 'training'-named sets and first_metric_only mismatches update
        # their best but never raise (callback.early_stopping)
        first_metric = eval_spec[0][2].split("@")[0] if eval_spec else ""
        eligible = tuple(
            (nm != "training")
            and (not spec["first_metric_only"]
                 or mn.split("@")[0] == first_metric)
            for (_vi, nm, mn, _h) in eval_spec)
        es_spec = {"stopping_rounds": int(spec["stopping_rounds"]),
                   "first_metric_only": bool(spec["first_metric_only"]),
                   "eligible": eligible}
    # epoch size: explicit superepoch wins; auto sizes to the fused
    # chunk, bounded by the early-stop horizon so a stop wastes at most
    # ~one epoch of post-stop (zeroed) in-scan iterations
    if cfg.superepoch > 0:
        base_k = cfg.superepoch
    elif es_spec is not None:
        base_k = max(2, min(cfg.fused_chunk,
                            es_spec["stopping_rounds"]))
    else:
        base_k = cfg.fused_chunk
    return max(int(base_k), 2), eval_spec, es_spec


def _dataset_raw(ds: Dataset):
    if ds.raw_data is not None:
        return ds.raw_data
    if ds._raw_input is not None:
        return ds._raw_input
    raise ValueError("init_model needs the training data raw values "
                     "(construct the Dataset with free_raw_data=False)")


class CVBooster:
    """Container of per-fold boosters (engine.py:264 analog)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, b: Booster) -> None:
        self.boosters.append(b)

    def __getattr__(self, name):
        def _handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return _handler


def _make_folds(ds: Dataset, nfold: int, stratified: bool, shuffle: bool,
                seed: int, cfg: Config):
    ds.construct(cfg)
    n = ds.num_data
    rng = np.random.RandomState(seed)
    if ds.metadata.query_boundaries is not None:
        # group-aware folds: the reference delegates to sklearn's
        # GroupKFold for ranking cv (engine.py _make_n_folds uses
        # _LGBMGroupKFold), so a user passing folds=GroupKFold(n) gets
        # IDENTICAL splits to nfold=n — keep that equivalence
        sizes = np.diff(ds.metadata.query_boundaries)
        groups = np.repeat(np.arange(len(sizes)), sizes)
        from sklearn.model_selection import GroupKFold
        yield from GroupKFold(n_splits=nfold).split(
            np.empty((n, 1)), groups=groups)
        return
    if stratified and cfg.objective in ("binary", "multiclass", "multiclassova"):
        label = np.asarray(ds.metadata.label).astype(np.int64)
        idx_by_class = [np.nonzero(label == c)[0] for c in np.unique(label)]
        folds = [[] for _ in range(nfold)]
        for idx in idx_by_class:
            if shuffle:
                idx = idx[rng.permutation(len(idx))]
            for fi, part in enumerate(np.array_split(idx, nfold)):
                folds[fi].append(part)
        for fi in range(nfold):
            test = np.concatenate(folds[fi])
            mask = np.zeros(n, bool)
            mask[test] = True
            yield np.nonzero(~mask)[0], np.nonzero(mask)[0]
        return
    order = rng.permutation(n) if shuffle else np.arange(n)
    for part in np.array_split(order, nfold):
        mask = np.zeros(n, bool)
        mask[part] = True
        yield np.nonzero(~mask)[0], np.nonzero(mask)[0]


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       feature_name="auto", categorical_feature="auto",
       fpreproc=None, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """K-fold cross-validation (engine.py:375 analog).

    fpreproc: ``f(fold_train, fold_valid, params) -> (train, valid,
    params)`` applied per fold before training (the reference's
    preprocessing hook).  eval_train_metric adds ``train <metric>-mean``
    series alongside the ``valid`` ones.
    """
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    from .config import canonical_params
    if "num_iterations" in canonical_params(params):
        # params win unconditionally, like train() (reference pops the
        # alias in both entry points)
        num_boost_round = Config(params).num_iterations
    if feature_name != "auto" and not train_set._constructed:
        train_set.set_feature_name(feature_name)
    if categorical_feature != "auto" and not train_set._constructed:
        train_set.set_categorical_feature(categorical_feature)
    cfg = Config(params)
    if not train_set._constructed and train_set.params:
        # dataset's own params are the binning base, cv params override
        # (reference _update_params semantics — see Booster.__init__)
        from .config import canonical_params
        cfg = Config({**canonical_params(train_set.params),
                      **canonical_params(params)})
    train_set.construct(cfg)

    if folds is None:
        folds = list(_make_folds(train_set, nfold, stratified, shuffle, seed, cfg))
    elif hasattr(folds, "split"):
        # scikit-learn splitter object (reference cv accepts these):
        # split over row indices, group-aware when the splitter wants it
        lbl = train_set.get_label()
        g = train_set.get_group()
        groups = np.repeat(np.arange(len(g)), g) if g is not None else None
        folds = list(folds.split(np.empty((train_set.num_data, 1)),
                                 y=lbl, groups=groups))

    cvbooster = CVBooster()
    results = collections.defaultdict(list)
    for (tr_idx, te_idx) in folds:
        # subset() reconstructs per-fold query groups from the parent's
        # boundaries itself
        tr = train_set.subset(tr_idx)
        te = train_set.subset(te_idx)
        fold_params = params
        if fpreproc is not None:
            tr, te, fold_params = fpreproc(tr, te, dict(params))
        bst = Booster(params=dict(fold_params), train_set=tr)
        bst._train_data_name = "train"
        bst.add_valid(te, "valid")
        cvbooster.append(bst)

    # lockstep boosting (the reference's CVBooster: every fold advances
    # one iteration, then the AGGREGATED metrics go to the callbacks as
    # ('cv_agg', '<set> <metric>', mean, higher_better, stdv) 5-tuples —
    # which is what gives cv early stopping and cv record_evaluation
    # their reference semantics)
    cbs = list(callbacks or [])
    cfg2 = Config(params)
    if cfg2.early_stopping_round and cfg2.early_stopping_round > 0:
        cbs.append(callback_mod.early_stopping(
            cfg2.early_stopping_round, cfg2.first_metric_only,
            cfg2.verbosity > 0))
    cbs_before = [c for c in cbs if getattr(c, "before_iteration", False)]
    cbs_after = [c for c in cbs if not getattr(c, "before_iteration", False)]
    cbs_before.sort(key=lambda c: getattr(c, "order", 0))
    cbs_after.sort(key=lambda c: getattr(c, "order", 0))
    best_iter = -1      # stays -1 unless early stopping fires (reference)
    for i in range(num_boost_round):
        env = CallbackEnv(model=cvbooster, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=None)
        for cb in cbs_before:
            cb(env)
        per_key: Dict[str, list] = collections.OrderedDict()
        hib_of: Dict[str, bool] = {}
        for bst in cvbooster.boosters:
            bst.update(fobj=fobj)
            one = list(bst.eval_train(feval)) if eval_train_metric else []
            one.extend(bst.eval_valid(feval))
            for (nm, met, val, hib) in one:
                key = f"{nm} {met}"
                per_key.setdefault(key, []).append(val)
                hib_of[key] = hib
        agg = [("cv_agg", k, float(np.mean(v)), hib_of[k], float(np.std(v)))
               for k, v in per_key.items()]
        for (_, k, mean, _h, std) in agg:
            results[f"{k}-mean"].append(mean)
            results[f"{k}-stdv"].append(std)
        env = CallbackEnv(model=cvbooster, params=params, iteration=i,
                          begin_iteration=0, end_iteration=num_boost_round,
                          evaluation_result_list=agg)
        try:
            for cb in cbs_after:
                cb(env)
        except EarlyStopException as e:
            best_iter = e.best_iteration + 1
            for b in cvbooster.boosters:
                b.best_iteration = best_iter
            # the reference trims the history to the best iteration
            for k in results:
                results[k] = results[k][:best_iter]
            break
    out = dict(results)
    if return_cvbooster:
        cvbooster.best_iteration = best_iter
        out["cvbooster"] = cvbooster
    return out


