"""EFB — exclusive feature bundling.

Analog of the reference's ``Dataset::FindGroups`` / ``FastFeatureBundling``
(/root/reference/src/io/dataset.cpp:100, :239): sparse, mutually-exclusive
features (e.g. one-hot blocks) are folded into one shared column so the
binned matrix narrows from F to G columns — on TPU this cuts the HBM bytes
streamed per histogram pass, which is the bandwidth-bound term.

Scheme (bundle of features j1..jk, each with default bin 0):
  group bin 0            = every constituent at its default bin
  group bins [off_j, off_j + nb_j - 1)  = feature j's bins 1..nb_j-1
Per-feature histograms are reconstructed on device by a gather over the
group histogram plus the reference's FixHistogram trick
(/root/reference/src/io/dataset.cpp:1292): the default bin is recovered as
``leaf_total - sum(other bins)``.  With ``max_conflict_rate=0`` (default)
bundling is exactly lossless — split decisions match the unbundled run
bit-for-bit; a nonzero rate trades accuracy for width like the reference.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np


class EFBInfo(NamedTuple):
    """Bundling description, feature indices in used-feature slot space."""
    groups: List[List[int]]          # per group: constituent feature slots
    group_of_feat: np.ndarray        # [F] int32
    off_of_feat: np.ndarray          # [F] int32; -1 => identity (singleton)
    group_num_bin: np.ndarray        # [G] int32

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    @property
    def max_group_bin(self) -> int:
        return int(self.group_num_bin.max()) if len(self.group_num_bin) else 2

    @property
    def any_bundled(self) -> bool:
        return bool((self.off_of_feat >= 0).any())


def find_bundles(sample_bins: np.ndarray, num_bin: np.ndarray,
                 is_cat: np.ndarray, most_freq_bin: np.ndarray,
                 max_conflict_rate: float = 0.0,
                 max_group_bins: int = 2048,
                 dense_rate: float = 0.8) -> EFBInfo:
    """Greedy conflict-bounded grouping (FindGroups, dataset.cpp:100).

    sample_bins: [S, F] binned sample rows used for conflict counting.
    Only numerical features whose default (most frequent) bin is 0 and whose
    non-default rate is <= dense_rate are bundling candidates; everything
    else gets a singleton group.  ``max_group_bins`` bounds a bundle's bin
    axis so the histogram row-block tile ([block, group_bins]
    in VMEM) stays well under the ~16 MB VMEM budget — oversize bundles are
    split into multiple groups automatically.
    """
    s, f = sample_bins.shape
    budget = int(max_conflict_rate * s)
    nz_count = (sample_bins != 0).sum(axis=0)   # [F] non-default counts

    eligible = [j for j in range(f)
                if not is_cat[j] and most_freq_bin[j] == 0
                and nz_count[j] <= dense_rate * s]
    # densest first so heavy features seed groups (reference sorts by
    # conflict count; non-zero count is the same ordering at rate 0)
    eligible.sort(key=lambda j: -int(nz_count[j]))

    # the reference caps the per-feature scan at max_search_group total:
    # max_search_group-1 randomly sampled groups + the newest group
    # (dataset.cpp:106 and :138, rand.Sample(last, max_search_group-1))
    # — without the cap wide unbundleable data (Allstate-shaped 4228
    # columns) degenerates to an O(F^2 * S) scan.  The scanned groups'
    # conflict counts are ONE [search, S] @ [S] matvec per feature rather
    # than a python loop of masked sums.
    max_search_group = 100
    # ...but only as a FALLBACK: the sampled subset hits the one
    # compatible group with probability ~max_search_group/ngr, which
    # shatters real bundles on data with hundreds of them (400 exclusive
    # 5-blocks collapsed to 400 groups under exact search degrade to
    # ~1600 under blind sampling).  The cap exists to bound the
    # O(F * ngr * S) scan on DEGENERATE width (unbundleable data where
    # ngr ~ F); below full_search_groups the exact matvec is affordable,
    # so correctness wins and the sample only kicks in past it.
    full_search_groups = 512
    grp_rng = np.random.RandomState(s)
    # group occupancy rows are allocated geometrically as groups actually
    # form (a full [eligible, S] matrix would be ~GBs on Allstate-shaped
    # 4228 x 200k samples that bundle into a few dozen groups); the
    # per-feature non-default mask is a strided column read, never a
    # [S, F] bool materialization
    cap = 64
    mask_arr = np.zeros((cap, s), np.uint8)
    bins_arr = np.zeros(cap, np.int64)                  # 1 + sum(nb-1)
    confl_arr = np.zeros(cap, np.int64)
    groups: List[List[int]] = []
    ngr = 0
    for j in eligible:
        nb1 = int(num_bin[j]) - 1
        nzj = (sample_bins[:, j] != 0).astype(np.uint8)
        if ngr <= full_search_groups:
            search = np.arange(ngr)
        else:
            idx = grp_rng.choice(ngr - 1, size=max_search_group - 1,
                                 replace=False)
            search = np.concatenate([[ngr - 1], idx])
        hit = -1
        if len(search):
            # int64 accumulation: a uint8 matvec would wrap counts at 256
            # and admit heavily-conflicting features into "exclusive"
            # bundles (the conflict sample is up to 200k rows)
            counts = mask_arr[search] @ nzj.astype(np.int64)
            ok = (bins_arr[search] + nb1 <= max_group_bins) \
                & (confl_arr[search] + counts <= budget)
            hits = np.nonzero(ok)[0]
            if len(hits):
                hit = int(hits[0])
        if hit >= 0:
            gi = int(search[hit])
            groups[gi].append(j)
            mask_arr[gi] |= nzj
            confl_arr[gi] += int(counts[hit])
            bins_arr[gi] += nb1
        else:
            if ngr == cap:
                cap *= 2
                mask_arr = np.concatenate(
                    [mask_arr, np.zeros((cap - ngr, s), np.uint8)])
                bins_arr = np.concatenate(
                    [bins_arr, np.zeros(cap - ngr, np.int64)])
                confl_arr = np.concatenate(
                    [confl_arr, np.zeros(cap - ngr, np.int64)])
            groups.append([j])
            mask_arr[ngr] = nzj
            bins_arr[ngr] = 1 + nb1
            ngr += 1
    group_bins = [int(b) for b in bins_arr[:ngr]]

    # drop the synthetic bin-0 for groups that stayed singletons, and add
    # singleton groups for ineligible features
    final_groups: List[List[int]] = []
    final_bins: List[int] = []
    for gi, g in enumerate(groups):
        if len(g) == 1:
            final_groups.append(g)
            final_bins.append(int(num_bin[g[0]]))
        else:
            final_groups.append(g)
            final_bins.append(group_bins[gi])
    in_bundle = {j for g in final_groups for j in g}
    for j in range(f):
        if j not in in_bundle:
            final_groups.append([j])
            final_bins.append(int(num_bin[j]))

    group_of = np.zeros(f, np.int32)
    off_of = np.full(f, -1, np.int32)
    for gi, g in enumerate(final_groups):
        if len(g) == 1:
            group_of[g[0]] = gi
        else:
            off = 1
            for j in g:
                group_of[j] = gi
                off_of[j] = off
                off += int(num_bin[j]) - 1
    return EFBInfo(groups=final_groups, group_of_feat=group_of,
                   off_of_feat=off_of,
                   group_num_bin=np.asarray(final_bins, np.int32))


def bin_grouped(feature_cols, efb: EFBInfo, num_data: int) -> np.ndarray:
    """Fold per-feature bin columns into the grouped matrix [N, G].

    ``feature_cols(j) -> [N] int array`` supplies feature j's bins lazily so
    the full [N, F] matrix never materializes for wide sparse data.
    """
    dtype = np.uint8 if efb.max_group_bin <= 256 else np.uint16
    out = np.zeros((num_data, efb.num_groups), dtype=dtype)
    for gi, g in enumerate(efb.groups):
        if len(g) == 1:
            out[:, gi] = feature_cols(g[0]).astype(dtype)
        else:
            col = np.zeros(num_data, dtype=np.int64)
            for j in g:
                b = feature_cols(j)
                nzr = b != 0
                col[nzr] = int(efb.off_of_feat[j]) + b[nzr] - 1
            out[:, gi] = col.astype(dtype)
    return out


def unbundle(binned_grouped: np.ndarray, efb: EFBInfo,
             num_bin: np.ndarray) -> np.ndarray:
    """Reconstruct the per-feature binned matrix [N, F] (for learners that
    do not take the grouped layout, e.g. the distributed shard_map path)."""
    f = len(efb.group_of_feat)
    dtype = np.uint8 if int(num_bin.max()) <= 256 else np.uint16
    out = np.zeros((binned_grouped.shape[0], f), dtype=dtype)
    for j in range(f):
        g = int(efb.group_of_feat[j])
        gcol = binned_grouped[:, g].astype(np.int64)
        off = int(efb.off_of_feat[j])
        if off < 0:
            out[:, j] = gcol.astype(dtype)
        else:
            hi = off + int(num_bin[j]) - 1
            sel = (gcol >= off) & (gcol < hi)
            out[sel, j] = (gcol[sel] - off + 1).astype(dtype)
    return out


def expansion_maps(efb: EFBInfo, num_bin: np.ndarray, max_bin: int):
    """Precompute the device gather maps for group->feature histogram
    expansion: (col_idx [F, B] int32 with -1 = masked, fix0 [F] bool)."""
    f = len(efb.group_of_feat)
    col_idx = np.full((f, max_bin), -1, np.int32)
    fix0 = np.zeros(f, bool)
    for j in range(f):
        nb = int(num_bin[j])
        off = int(efb.off_of_feat[j])
        if off < 0:
            col_idx[j, :nb] = np.arange(nb)
        else:
            fix0[j] = True
            col_idx[j, 1:nb] = off + np.arange(nb - 1)
    return col_idx, fix0


class EFBDevice(NamedTuple):
    """Device-ready bundling state handed to the learner."""
    group_of_feat: object     # jax [F] int32
    col_idx: object           # jax [F, B] int32 gather map (-1 = masked)
    fix0: object              # jax [F] bool
    off_host: np.ndarray      # host [F] int32 (-1 identity)
    group_host: np.ndarray    # host [F] int32
    group_bins: int           # static: max bins over groups


def make_device_efb(efb: Optional[EFBInfo], num_bin: np.ndarray,
                    max_bin: int) -> Optional[EFBDevice]:
    if efb is None:
        return None
    import jax.numpy as jnp
    col_idx, fix0 = expansion_maps(efb, num_bin, max_bin)
    return EFBDevice(group_of_feat=jnp.asarray(efb.group_of_feat),
                     col_idx=jnp.asarray(col_idx), fix0=jnp.asarray(fix0),
                     off_host=np.asarray(efb.off_of_feat),
                     group_host=np.asarray(efb.group_of_feat),
                     group_bins=efb.max_group_bin)


def expand_group_hist(ghist, total, group_of_feat, col_idx, fix0):
    """Device op: group histogram [G, Bg, C] -> feature histogram [F, B, C].

    ``total`` [C] is the leaf's (grad, hess, count) sums, used for the
    FixHistogram default-bin reconstruction (dataset.cpp:1292 analog).
    """
    import jax.numpy as jnp
    src = jnp.take(ghist, group_of_feat, axis=0)          # [F, Bg, C]
    idx = jnp.clip(col_idx, 0, ghist.shape[1] - 1)
    fh = jnp.take_along_axis(src, idx[:, :, None], axis=1)
    fh = jnp.where((col_idx >= 0)[:, :, None], fh, 0.0)   # [F, B, C]
    rest = fh[:, 1:, :].sum(axis=1)
    bin0 = jnp.where(fix0[:, None], total[None, :] - rest, fh[:, 0, :])
    return fh.at[:, 0, :].set(bin0)
