"""Benchmark: HIGGS-shaped binary classification training throughput.

Mirrors the reference's headline experiment (docs/Experiments.rst: HIGGS,
500 iterations, num_leaves=255 -> 130.094 s on 2x E5-2690v4, i.e. 3.843
iters/s; GPU docs recommend 63 bins for accelerator runs,
docs/GPU-Performance.rst:108-124).  This round benches a 1M-row slice of
that shape at num_leaves=31, max_bin=63; ``vs_baseline`` is our steady-state
iters/s over the reference's full-size 3.843 iters/s.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np


def make_higgs_like(n: int, f: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
             + 0.4 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0).astype(np.float32)
    return x, y


def main():
    n, f = 1_000_000, 28
    iters = 100
    x, y = make_higgs_like(n, f)

    print("[bench] data ready; importing jax / claiming device...",
          file=sys.stderr, flush=True)
    t_dev = time.time()
    import jax
    print(f"[bench] devices={jax.devices()} ({time.time() - t_dev:.1f}s)",
          file=sys.stderr, flush=True)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.metrics import _auc

    params = {
        "objective": "binary",
        "num_leaves": 31,
        "learning_rate": 0.1,
        "max_bin": 63,
        "min_data_in_leaf": 20,
        "verbosity": 0,
    }
    t_bin0 = time.time()
    ds = lgb.Dataset(x, label=y)
    ds.construct()
    t_bin = time.time() - t_bin0

    bst = lgb.Booster(params=params, train_set=ds)
    # warmup: first iteration includes XLA compilation
    t0 = time.time()
    bst.update()
    t_compile = time.time() - t0

    t1 = time.time()
    for _ in range(iters - 1):
        bst.update()
    # force device sync
    np.asarray(bst._model.score)
    dt = time.time() - t1
    ips = (iters - 1) / dt

    auc = _auc(y, np.asarray(bst._model.train_score())[:, 0], None)
    print(f"[bench] bin={t_bin:.1f}s compile+iter1={t_compile:.1f}s "
          f"steady={dt:.1f}s for {iters-1} iters -> {ips:.2f} iters/s "
          f"train-AUC={auc:.4f}", file=sys.stderr)

    baseline_ips = 500.0 / 130.094  # reference HIGGS CPU (Experiments.rst:113)
    print(json.dumps({
        "metric": "higgs1m_binary_train_iters_per_sec",
        "value": round(ips, 3),
        "unit": "iters/s (1M rows x 28 feat, 31 leaves, 63 bins)",
        "vs_baseline": round(ips / baseline_ips, 3),
    }))


if __name__ == "__main__":
    main()
