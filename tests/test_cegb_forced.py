"""CEGB + forced-splits tests (test_engine.py forced_splits / cegb analog)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


class TestCEGB:
    def test_coupled_penalty_discourages_feature(self, binary_data):
        x, y = binary_data
        base = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                "min_data_in_leaf": 5}
        bst0 = lgb.train(base, lgb.Dataset(x, label=y), num_boost_round=10)
        imp0 = bst0.feature_importance("split")
        top = int(np.argmax(imp0))
        # huge coupled penalty on the top feature bans it
        penalties = [0.0] * x.shape[1]
        penalties[top] = 1e9
        p = dict(base, cegb_tradeoff=1.0,
                 cegb_penalty_feature_coupled=penalties)
        bst1 = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        imp1 = bst1.feature_importance("split")
        assert imp1[top] == 0

    def test_split_penalty_prunes(self, binary_data):
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
             "min_data_in_leaf": 5, "cegb_tradeoff": 1.0,
             "cegb_penalty_split": 1e9}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=3)
        # penalty so large no split is worth it -> stump trees
        assert all(t.num_leaves == 1 for t in bst.trees)


class TestForcedSplits:
    def test_forced_top(self, binary_data, tmp_path):
        x, y = binary_data
        forced = {"feature": 5, "threshold": 0.0,
                  "left": {"feature": 6, "threshold": 0.5}}
        path = str(tmp_path / "forced.json")
        with open(path, "w") as f:
            json.dump(forced, f)
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "forcedsplits_filename": path}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=5)
        for t in bst.trees:
            assert t.split_feature[0] == 5          # forced root
            # node 1 (left child of root) forced to feature 6
            if t.num_nodes() > 1 and t.left_child[0] == 1:
                assert t.split_feature[1] == 6
        from lightgbm_tpu.metrics import _auc
        assert _auc(y, bst.predict(x, raw_score=True), None) > 0.9


class TestCEGBMasked:
    """CEGB on the one-program masked grower (in-graph penalty vectors +
    [F] used-feature state, grower.py) — previously partitioned-only."""

    def _data(self):
        rs = np.random.RandomState(3)
        n = 3000
        x = rs.randn(n, 8)
        y = (x[:, 0] + 0.8 * x[:, 1] + 0.6 * x[:, 2]
             + 0.1 * rs.randn(n) > 0).astype(np.float32)
        return x, y

    def test_masked_matches_partitioned(self):
        x, y = self._data()
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "verbose": -1,
             "cegb_tradeoff": 0.5,
             "cegb_penalty_feature_coupled": [5.0] * 8}
        b_m = lgb.train({**p, "tpu_learner": "masked"},
                        lgb.Dataset(x, label=y), num_boost_round=8)
        b_p = lgb.train({**p, "tpu_learner": "partitioned"},
                        lgb.Dataset(x, label=y), num_boost_round=8)
        assert b_m._model._learner_kind == "masked"
        for tm, tp in zip(b_m.trees, b_p.trees):
            np.testing.assert_array_equal(tm.split_feature, tp.split_feature)
            np.testing.assert_allclose(tm.leaf_value, tp.leaf_value,
                                       rtol=1e-5, atol=1e-7)

    def test_masked_coupled_concentrates_features(self):
        """Coupled acquisition penalties make later splits prefer already-
        bought features (the CEGB point)."""
        x, y = self._data()
        base = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                "min_data_in_leaf": 5, "verbose": -1,
                "tpu_learner": "masked"}
        b0 = lgb.train(base, lgb.Dataset(x, label=y), num_boost_round=10)
        b1 = lgb.train({**base, "cegb_tradeoff": 1.0,
                        "cegb_penalty_feature_coupled": [50.0] * 8},
                       lgb.Dataset(x, label=y), num_boost_round=10)
        nfeat = [len({int(f) for t in b.trees
                      for f in np.asarray(t.split_feature)[:t.num_leaves - 1]})
                 for b in (b0, b1)]
        assert nfeat[1] <= nfeat[0]
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, b1.predict(x)) > 0.8

    def test_masked_fused_equals_per_iter(self):
        x, y = self._data()
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "verbose": -1, "tpu_learner": "masked",
             "cegb_tradeoff": 0.7, "cegb_penalty_split": 1e-5,
             "cegb_penalty_feature_coupled": [10.0] * 8}
        b_it = lgb.train(dict(p, fused_chunk=0), lgb.Dataset(x, label=y),
                         num_boost_round=8)
        b_fu = lgb.train(dict(p, fused_chunk=4), lgb.Dataset(x, label=y),
                         num_boost_round=8)
        np.testing.assert_array_equal(b_it.predict(x), b_fu.predict(x))

    def test_masked_batched_cegb(self):
        x, y = self._data()
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "verbose": -1, "tpu_learner": "masked",
             "split_batch": 4, "cegb_tradeoff": 0.7,
             "cegb_penalty_feature_coupled": [10.0] * 8}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=8)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(y, bst.predict(x)) > 0.8

    def test_dist_cegb_refused(self):
        x, y = self._data()
        with pytest.raises(ValueError, match="CEGB"):
            lgb.train({"objective": "binary", "tree_learner": "data",
                       "cegb_penalty_split": 1e-4, "verbose": -1},
                      lgb.Dataset(x, label=y), num_boost_round=2)
