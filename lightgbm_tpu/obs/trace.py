"""Span/trace API: nested spans, JSONL sink, Perfetto export, fence().

The reference attributes time with RAII ``FunctionTimer`` scopes into a
``global_timer`` table (common.h:978-1056).  On an asynchronous XLA
runtime wall-clock scopes lie unless each span's device work is fenced
— and PROFILE.md measured that ``jax.block_until_ready`` itself lies on
the axon backend (returns in ~1 ms with work still queued), so the only
trustworthy fence is a ``jax.device_get`` of a value *derived from* the
work being timed.  ``fence()`` below is that trick, packaged; every
hand-rolled copy of it (tools/profile_iter.py, bench_hist.py) should go
through here.

Event model: spans are Chrome-trace "complete" events (``ph": "X"``)
with microsecond ``ts``/``dur`` on the monotonic clock, written one
JSON object per line (JSONL) so a crash loses at most the line in
flight.  ``jsonl_to_chrome`` wraps the same records into the
``{"traceEvents": [...]}`` envelope Perfetto / chrome://tracing load
directly — the round trip is loss-free because the JSONL records ARE
trace events.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


def fence(x: Any = None) -> Any:
    """Reliable device fence: block until every array in ``x`` has
    actually been computed, then return ``x`` unchanged (chainable).

    ``jax.block_until_ready`` is NOT used: on backends where dispatch is
    tunneled (PROFILE.md's axon measurements) it can return with work
    still queued.  Fetching a tiny slice *derived from* each array
    cannot lie — the transfer completes only after the producing
    computation does.  Cost: one scalar-sized host round trip (~wire
    latency), zero extra device compute beyond a 1-element slice.

    Arrays that are not fully addressable from this process (multi-host
    shards) fall back to ``block_until_ready`` — a cross-process fetch
    would turn the fence into a collective.
    """
    import jax
    import jax.numpy as jnp

    if x is None:
        # fence the whole stream: a fresh trivial computation is queued
        # behind everything already dispatched on the default device
        jax.device_get(jnp.zeros(()) + 0.0)
        return x
    probes = []
    for leaf in jax.tree_util.tree_leaves(x):
        if not isinstance(leaf, jax.Array):
            continue
        if getattr(leaf, "is_fully_addressable", True):
            # a 1-element corner slice, NOT ravel()[:1]: ravel of a 2-D
            # array is a real reshape that copies the whole buffer
            probes.append(leaf[(slice(0, 1),) * leaf.ndim])
        else:
            jax.block_until_ready(leaf)       # sync-ok: multi-host fallback
    if probes:
        jax.device_get(probes)
    return x


class Span:
    """One open span; closes via context-manager exit or ``end()``.

    ``end(result)`` fences ``result`` before taking the stop timestamp
    — the PROFILE.md discipline: a span that timed asynchronous device
    work must wait on a value derived from that work, or the time leaks
    into whoever blocks next.  ``end()`` with no result (and plain
    ``with``-exit) records wall time without touching the device."""

    __slots__ = ("tracer", "name", "args", "t0", "_done")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.t0 = tracer.now()
        self._done = False

    def __enter__(self) -> "Span":
        return self

    def end(self, result: Any = None) -> float:
        """Close the span, fencing ``result`` first when given; returns
        the span duration in seconds."""
        if self._done:
            return 0.0
        self._done = True
        if result is not None:
            fence(result)
        dur = self.tracer.now() - self.t0
        self.tracer._emit(self.name, self.t0, dur, self.args)
        return dur

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


class Tracer:
    """Nested-span tracer with an optional JSONL sink.

    Spans nest naturally (the Chrome trace model infers nesting from
    containment of [ts, ts+dur) per tid), so no explicit stack is kept;
    ``span()`` is re-entrant and thread-safe.  Events are retained
    in-memory (for programmatic export) AND streamed to the sink the
    moment each span closes.
    """

    def __init__(self, sink_path: Optional[str] = None,
                 pid: Optional[int] = None):
        self.events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._sink = None
        self._sink_path = sink_path
        if sink_path:
            d = os.path.dirname(os.path.abspath(sink_path))
            os.makedirs(d, exist_ok=True)
            self._sink = open(sink_path, "a", buffering=1)
        if pid is None:
            try:
                import jax
                pid = jax.process_index()
            except Exception:
                pid = 0
        self.pid = pid

    @staticmethod
    def now() -> float:
        """Monotonic seconds (perf_counter: highest-resolution monotonic
        clock Python exposes)."""
        return time.perf_counter()

    def span(self, name: str, **args: Any) -> Span:
        return Span(self, name, args)

    def instant(self, name: str, **args: Any) -> None:
        """Zero-duration marker event (``ph: "i"``)."""
        self._emit(name, self.now(), 0.0, args, ph="i")

    def _emit(self, name: str, t0: float, dur: float,
              args: Dict[str, Any], ph: str = "X") -> None:
        ev = {"name": name, "ph": ph, "ts": round(t0 * 1e6, 3),
              "dur": round(dur * 1e6, 3), "pid": self.pid,
              "tid": threading.get_ident() & 0xFFFF}
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)
            if self._sink is not None:
                self._sink.write(json.dumps(ev) + "\n")

    def durations(self, name: str) -> List[float]:
        """All recorded durations (seconds) of spans named ``name``."""
        with self._lock:                  # _emit appends concurrently
            events = list(self.events)
        return [e["dur"] / 1e6 for e in events
                if e["name"] == name and e["ph"] == "X"]

    def flush(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.flush()

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None

    def export_chrome(self, path: str) -> None:
        """Write the in-memory events as a Chrome/Perfetto trace file."""
        with self._lock:
            events = list(self.events)
        _write_chrome(path, events)


def timed_fenced(fn, iters: int = 10, tracer: Optional[Tracer] = None,
                 name: str = "timed") -> tuple:
    """Run ``fn`` ``iters`` times, fencing its return value each rep;
    returns (min_seconds, avg_seconds).  The successor of the private
    ``bench_phase`` helpers in tools/ — one definition of "how we time a
    device-side phase" (PROFILE.md methodology)."""
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fence(fn())
        dt = time.perf_counter() - t0
        ts.append(dt)
        if tracer is not None:
            tracer._emit(name, t0, dt, {})
    return min(ts), sum(ts) / len(ts)


# -- JSONL <-> Perfetto ----------------------------------------------------

def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL trace back into event dicts (skipping any torn
    trailing line from a crash)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue          # torn final line: crash mid-write
    return out


def _write_chrome(path: str, events: List[Dict[str, Any]]) -> None:
    with open(path, "w") as f:
        f.write(json.dumps({"traceEvents": events,
                            "displayTimeUnit": "ms"}))


def jsonl_to_chrome(src: str, dst: str) -> int:
    """Convert a JSONL event sink into a Chrome-trace JSON file that
    Perfetto (ui.perfetto.dev) and chrome://tracing load directly;
    returns the event count."""
    events = read_jsonl(src)
    _write_chrome(dst, events)
    return len(events)
