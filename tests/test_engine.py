"""End-to-end training tests (test_engine.py analog, SURVEY.md §4):
objective families, quality thresholds on synthetic data, early stopping,
callbacks, model round-trips.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.metrics import _auc


def _train_binary(x, y, params=None, rounds=30, valid=None):
    p = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
         "max_bin": 63, "min_data_in_leaf": 5, "verbosity": 0}
    p.update(params or {})
    ds = lgb.Dataset(x, label=y)
    vs = [lgb.Dataset(v[0], label=v[1], reference=ds) for v in (valid or [])]
    return lgb.train(p, ds, num_boost_round=rounds, valid_sets=vs or None)


class TestBinary:
    def test_auc_quality(self, binary_data):
        x, y = binary_data
        bst = _train_binary(x[:3000], y[:3000])
        pred = bst.predict(x[3000:], raw_score=True)
        auc = _auc(y[3000:], pred, None)
        assert auc > 0.97, f"AUC too low: {auc}"

    def test_predict_probability(self, binary_data):
        x, y = binary_data
        bst = _train_binary(x, y, rounds=10)
        p = bst.predict(x[:100])
        assert (p >= 0).all() and (p <= 1).all()
        raw = bst.predict(x[:100], raw_score=True)
        np.testing.assert_allclose(p, 1 / (1 + np.exp(-raw)), rtol=1e-5)

    def test_eval_improves(self, binary_data):
        x, y = binary_data
        rec = {}
        p = {"objective": "binary", "num_leaves": 15, "metric": ["binary_logloss"],
             "max_bin": 63, "min_data_in_leaf": 5}
        ds = lgb.Dataset(x[:3000], label=y[:3000])
        vds = lgb.Dataset(x[3000:], label=y[3000:], reference=ds)
        lgb.train(p, ds, num_boost_round=20, valid_sets=[vds],
                  callbacks=[lgb.record_evaluation(rec)])
        ll = rec["valid_0"]["binary_logloss"]
        assert len(ll) == 20
        assert ll[-1] < ll[0] * 0.7

    def test_early_stopping(self, binary_data):
        x, y = binary_data
        rs = np.random.RandomState(9)
        y_noise = rs.permutation(y[3000:])  # uninformative valid labels
        p = {"objective": "binary", "num_leaves": 31, "metric": ["auc"],
             "max_bin": 63, "early_stopping_round": 3}
        ds = lgb.Dataset(x[:3000], label=y[:3000])
        vds = lgb.Dataset(x[3000:], label=y_noise, reference=ds)
        bst = lgb.train(p, ds, num_boost_round=100, valid_sets=[vds])
        assert bst.best_iteration > 0
        assert bst.current_iteration < 100

    def test_weights_respected(self, binary_data):
        x, y = binary_data
        w = np.where(y > 0, 10.0, 1.0)
        bst = _train_binary(x, y, rounds=10)
        ds = lgb.Dataset(x, label=y, weight=w)
        bstw = lgb.train({"objective": "binary", "num_leaves": 15,
                          "max_bin": 63}, ds, num_boost_round=10)
        # heavier positive weight pushes predictions up
        assert bstw.predict(x).mean() > bst.predict(x).mean()


class TestRegression:
    def test_l2_quality(self, regression_data):
        x, y = regression_data
        p = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
             "learning_rate": 0.1, "min_data_in_leaf": 5}
        ds = lgb.Dataset(x[:3000], label=y[:3000])
        bst = lgb.train(p, ds, num_boost_round=60)
        pred = bst.predict(x[3000:])
        mse = float(np.mean((pred - y[3000:]) ** 2))
        var = float(np.var(y[3000:]))
        assert mse < 0.4 * var, f"MSE {mse} vs var {var}"

    def test_l1_median_renewal(self):
        rs = np.random.RandomState(0)
        x = rs.randn(2000, 5)
        y = x[:, 0] + 0.05 * rs.randn(2000)
        p = {"objective": "regression_l1", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=40)
        mae = float(np.mean(np.abs(bst.predict(x) - y)))
        assert mae < 0.5 * np.mean(np.abs(y - np.median(y)))

    @pytest.mark.parametrize("obj", ["huber", "fair", "quantile", "mape"])
    def test_robust_objectives_run(self, obj, regression_data):
        x, y = regression_data
        p = {"objective": obj, "num_leaves": 7, "max_bin": 31}
        bst = lgb.train(p, lgb.Dataset(x[:1000], label=y[:1000]),
                        num_boost_round=5)
        assert np.isfinite(bst.predict(x[:50])).all()

    @pytest.mark.parametrize("obj", ["poisson", "gamma", "tweedie"])
    def test_positive_objectives(self, obj):
        rs = np.random.RandomState(1)
        x = rs.randn(1500, 5)
        y = np.exp(0.5 * x[:, 0] + 0.1 * rs.randn(1500))
        p = {"objective": obj, "num_leaves": 7, "max_bin": 31}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        pred = bst.predict(x[:100])
        assert (pred > 0).all()


class TestMulticlass:
    def test_softmax_quality(self):
        rs = np.random.RandomState(2)
        n = 3000
        x = rs.randn(n, 8)
        y = (x[:, 0] > 0.5).astype(int) + (x[:, 1] > 0).astype(int)
        p = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
             "max_bin": 63, "min_data_in_leaf": 5}
        bst = lgb.train(p, lgb.Dataset(x[:2000], label=y[:2000]),
                        num_boost_round=30)
        pred = bst.predict(x[2000:])
        assert pred.shape == (1000, 3)
        np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
        acc = (pred.argmax(axis=1) == y[2000:]).mean()
        assert acc > 0.85, f"accuracy {acc}"

    def test_ova(self):
        rs = np.random.RandomState(3)
        x = rs.randn(1500, 5)
        y = (x[:, 0] > 0).astype(int) * 2 + (x[:, 1] > 0).astype(int) * 0
        y = np.clip(y, 0, 2)
        p = {"objective": "multiclassova", "num_class": 3, "num_leaves": 7,
             "max_bin": 31}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        pred = bst.predict(x[:100])
        assert pred.shape == (100, 3)


class TestModelIO:
    def test_save_load_roundtrip(self, binary_data, tmp_path):
        x, y = binary_data
        bst = _train_binary(x, y, rounds=15)
        path = str(tmp_path / "model.txt")
        bst.save_model(path)
        bst2 = lgb.Booster(model_file=path)
        p1 = bst.predict(x[:500], raw_score=True)
        p2 = bst2.predict(x[:500], raw_score=True)
        np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-10)

    def test_model_string_roundtrip(self, regression_data):
        x, y = regression_data
        p = {"objective": "regression", "num_leaves": 7, "max_bin": 31}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=8)
        s = bst.model_to_string()
        assert "tree" in s and "end of trees" in s
        bst2 = lgb.Booster.model_from_string(s)
        np.testing.assert_allclose(bst.predict(x[:200]), bst2.predict(x[:200]),
                                   rtol=1e-6, atol=1e-10)

    def test_missing_values_in_predict(self):
        rs = np.random.RandomState(5)
        x = rs.randn(2000, 4)
        x[rs.rand(2000) < 0.2, 1] = np.nan
        y = (np.nan_to_num(x[:, 1], nan=2.0) > 0).astype(np.float32)
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
             "min_data_in_leaf": 5}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=10)
        xt = x[:100].copy()
        xt[:, 1] = np.nan
        pred = bst.predict(xt)
        assert np.isfinite(pred).all()
        # NaN rows should predict like the high-label group
        assert pred.mean() > 0.5


class TestSampling:
    def test_bagging(self, binary_data):
        x, y = binary_data
        bst = _train_binary(x, y, params={"bagging_fraction": 0.5,
                                          "bagging_freq": 1}, rounds=15)
        pred = bst.predict(x, raw_score=True)
        assert _auc(y, pred, None) > 0.9

    def test_goss(self, binary_data):
        x, y = binary_data
        bst = _train_binary(x, y, params={"data_sample_strategy": "goss"},
                            rounds=15)
        assert _auc(y, bst.predict(x, raw_score=True), None) > 0.9

    def test_feature_fraction(self, binary_data):
        x, y = binary_data
        bst = _train_binary(x, y, params={"feature_fraction": 0.6}, rounds=15)
        assert _auc(y, bst.predict(x, raw_score=True), None) > 0.9


class TestBoostingVariants:
    def test_dart(self, binary_data):
        x, y = binary_data
        bst = _train_binary(x, y, params={"boosting": "dart",
                                          "drop_rate": 0.2}, rounds=15)
        assert _auc(y, bst.predict(x, raw_score=True), None) > 0.9

    def test_rf(self, binary_data):
        x, y = binary_data
        bst = _train_binary(x, y, params={"boosting": "rf",
                                          "bagging_fraction": 0.7,
                                          "bagging_freq": 1}, rounds=10)
        assert _auc(y, bst.predict(x, raw_score=True), None) > 0.9


class TestCustomObjective:
    def test_fobj_feval(self, binary_data):
        x, y = binary_data
        ds = lgb.Dataset(x, label=y, params={"max_bin": 63})

        def fobj(preds, dataset):
            p = 1 / (1 + np.exp(-preds))
            return p - y, p * (1 - p)

        def feval(preds, dataset):
            p = 1 / (1 + np.exp(-preds))
            return ("my_err", float(np.mean((p > 0.5) != y)), False)

        p = {"objective": "custom", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5}
        bst = lgb.train(p, ds, num_boost_round=15, fobj=fobj, feval=feval)
        pred = bst.predict(x, raw_score=True)
        assert _auc(y, pred, None) > 0.95


class TestCV:
    def test_cv_binary(self, binary_data):
        x, y = binary_data
        res = lgb.cv({"objective": "binary", "num_leaves": 7, "max_bin": 31,
                      "metric": ["auc"]},
                     lgb.Dataset(x[:2000], label=y[:2000]),
                     num_boost_round=5, nfold=3)
        assert "valid auc-mean" in res
        assert len(res["valid auc-mean"]) == 5
        assert res["valid auc-mean"][-1] > 0.8


class TestContinuedTraining:
    def test_init_model(self, binary_data):
        x, y = binary_data
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 31}
        ds1 = lgb.Dataset(x, label=y, free_raw_data=False)
        bst1 = lgb.train(p, ds1, num_boost_round=5)
        ds2 = lgb.Dataset(x, label=y, free_raw_data=False)
        bst2 = lgb.train(p, ds2, num_boost_round=5, init_model=bst1)
        assert bst2.num_trees() == 10
        auc1 = _auc(y, bst1.predict(x, raw_score=True), None)
        auc2 = _auc(y, bst2.predict(x, raw_score=True), None)
        assert auc2 >= auc1 - 1e-6


class TestLeafRenewal:
    """VERDICT r3 task 10: leaf-renewal semantics asserted end-to-end for
    the percentile-renewing objectives (regression_objective.hpp
    RenewTreeOutput): the FIRST tree's stored leaf values must equal
    init + lr * weighted-percentile of the leaf's residuals — not the
    Newton outputs the grower computed."""

    @staticmethod
    def _data(n=800, seed=11):
        rng = np.random.RandomState(seed)
        x = rng.randn(n, 6)
        # skewed noise so mean-based leaf outputs differ measurably from
        # the percentile-renewed values
        y = (x[:, 0] * 2.0 + np.exp(rng.randn(n)) ).astype(np.float64)
        return x, y

    def _check(self, objective, q, weight_fn=None, extra=None):
        """``q`` is the percentile the renewal must hit (0.5 for L1/MAPE,
        the configured alpha for quantile)."""
        from lightgbm_tpu.objectives import _weighted_percentile
        x, y = self._data()
        lr = 0.3
        p = {"objective": objective, "num_leaves": 8, "max_bin": 63,
             "min_data_in_leaf": 20, "learning_rate": lr, "verbosity": -1}
        if extra:
            p.update(extra)
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=1)
        w = None if weight_fn is None else weight_fn(y)
        init0 = _weighted_percentile(
            np.asarray(y), None if w is None else np.asarray(w), q)
        leaves = np.asarray(bst.predict(x, pred_leaf=True))[:, 0]
        t = bst.trees[0]
        checked = 0
        for leaf in np.unique(leaves):
            rows = leaves == leaf
            if rows.sum() < 2:
                continue
            resid = y[rows] - init0
            wr = None if w is None else w[rows]
            want = init0 + lr * _weighted_percentile(np.asarray(resid), wr,
                                                     q)
            np.testing.assert_allclose(float(t.leaf_value[leaf]), want,
                                       rtol=1e-5, atol=1e-7,
                                       err_msg=f"{objective} leaf {leaf}")
            checked += 1
        assert checked >= 4, f"only {checked} leaves checked"

    def test_l1_renews_to_leaf_median(self):
        self._check("regression_l1", 0.5)

    def test_quantile_renews_to_alpha_percentile(self):
        self._check("quantile", 0.7, extra={"alpha": 0.7})

    def test_mape_renews_to_weighted_median(self):
        self._check("mape", 0.5,
                    weight_fn=lambda y: 1.0 / np.maximum(np.abs(y), 1.0))

    def test_renewal_differs_from_newton_output(self):
        # guard the guard: with renewal suppressed the values change
        x, y = self._data()
        p = {"objective": "regression_l1", "num_leaves": 8, "max_bin": 63,
             "min_data_in_leaf": 20, "learning_rate": 0.3, "verbosity": -1}
        bst = lgb.train(p, lgb.Dataset(x, label=y), num_boost_round=1)
        p2 = dict(p, objective="regression")     # L2: no renewal
        bst2 = lgb.train(p2, lgb.Dataset(x, label=y), num_boost_round=1)
        assert not np.allclose(bst.trees[0].leaf_value[:4],
                               bst2.trees[0].leaf_value[:4])
