// Native inference runtime + C API shim.
//
// TPU-native framework counterpart of the reference's C API prediction
// surface (include/LightGBM/c_api.h:749-1199, src/c_api.cpp Booster
// prediction paths, gbdt_prediction.cpp inner loop, tree.h:335-412
// NumericalDecision/CategoricalDecision).  Training runs in the JAX/XLA
// layer; this module gives deployments a dependency-free native predictor
// over the (LightGBM-compatible) text model format, exposed with
// ecosystem-parity LGBM_* entry points callable from C/ctypes/cffi.
//
// Built standalone:  g++ -O3 -fopenmp -shared -fPIC capi.cpp -o libcapi.so

#include <cmath>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <limits>
#include <sstream>
#include <string>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

thread_local std::string g_last_error;

int SetError(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

// ---------------------------------------------------------------------------
// parsing helpers
// ---------------------------------------------------------------------------

template <typename T>
std::vector<T> ParseArray(const std::string& s) {
  std::vector<T> out;
  std::istringstream is(s);
  double v;
  while (is >> v) out.push_back(static_cast<T>(v));
  return out;
}

// key=value map over one text block (header or a single tree)
struct KVBlock {
  std::vector<std::pair<std::string, std::string>> items;
  const std::string* Find(const std::string& key) const {
    for (const auto& kv : items)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
  std::string Get(const std::string& key, const std::string& dflt = "") const {
    const std::string* p = Find(key);
    return p ? *p : dflt;
  }
};

KVBlock ParseKV(const std::string& text) {
  KVBlock b;
  std::istringstream is(text);
  std::string line;
  while (std::getline(is, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    size_t eq = line.find('=');
    if (eq == std::string::npos) continue;
    b.items.emplace_back(line.substr(0, eq), line.substr(eq + 1));
  }
  return b;
}

// ---------------------------------------------------------------------------
// tree
// ---------------------------------------------------------------------------

constexpr int kCategoricalBit = 1;  // decision_type bit 0
constexpr int kDefaultLeftBit = 2;  // bit 1
constexpr int kMissingShift = 2;    // bits 2-3: 0 none / 1 zero / 2 nan

struct Tree {
  int num_leaves = 1;
  std::vector<int> split_feature, decision_type, left_child, right_child;
  std::vector<double> threshold, leaf_value;
  int num_cat = 0;
  std::vector<int> cat_boundaries;
  std::vector<uint32_t> cat_threshold;
  bool is_linear = false;
  std::vector<double> leaf_const;
  std::vector<std::vector<int>> leaf_features;
  std::vector<std::vector<double>> leaf_coeff;

  bool CatContains(int cat_idx, double v) const {
    if (!std::isfinite(v) || v < 0) return false;
    int iv = static_cast<int>(v);
    int lo = cat_boundaries[cat_idx], hi = cat_boundaries[cat_idx + 1];
    int nbits = 32 * (hi - lo);
    if (iv >= nbits) return false;
    return (cat_threshold[lo + iv / 32] >> (iv % 32)) & 1u;
  }

  int PredictLeaf(const double* row) const {
    if (num_leaves <= 1) return 0;
    int node = 0;
    while (node >= 0) {
      int dt = decision_type[node];
      double v = row[split_feature[node]];
      bool left;
      if (dt & kCategoricalBit) {
        left = CatContains(static_cast<int>(threshold[node]), v);
      } else {
        int miss = (dt >> kMissingShift) & 3;
        bool isnan = std::isnan(v);
        if (isnan && miss != 2) { v = 0.0; isnan = false; }
        if (isnan)
          left = (dt & kDefaultLeftBit) != 0;
        else
          left = v <= threshold[node];
      }
      node = left ? left_child[node] : right_child[node];
    }
    return ~node;
  }

  double Predict(const double* row) const {
    int leaf = PredictLeaf(row);
    if (is_linear && !leaf_features[leaf].empty()) {
      double out = leaf_const[leaf];
      const auto& feats = leaf_features[leaf];
      const auto& coef = leaf_coeff[leaf];
      for (size_t i = 0; i < feats.size(); ++i) {
        double v = row[feats[i]];
        if (std::isnan(v)) return leaf_value[leaf];  // NaN fallback
        out += coef[i] * v;
      }
      return out;
    }
    return leaf_value[leaf];
  }

  static Tree FromBlock(const std::string& text) {
    KVBlock kv = ParseKV(text);
    Tree t;
    t.num_leaves = std::stoi(kv.Get("num_leaves", "1"));
    int n = t.num_leaves > 1 ? t.num_leaves - 1 : 0;
    t.split_feature = ParseArray<int>(kv.Get("split_feature"));
    t.threshold = ParseArray<double>(kv.Get("threshold"));
    t.decision_type = ParseArray<int>(kv.Get("decision_type"));
    t.left_child = ParseArray<int>(kv.Get("left_child"));
    t.right_child = ParseArray<int>(kv.Get("right_child"));
    t.leaf_value = ParseArray<double>(kv.Get("leaf_value"));
    t.split_feature.resize(n, 0);
    t.threshold.resize(n, 0.0);
    t.decision_type.resize(n, 0);
    t.left_child.resize(n, -1);
    t.right_child.resize(n, -2);
    t.leaf_value.resize(t.num_leaves, 0.0);
    t.num_cat = std::stoi(kv.Get("num_cat", "0"));
    if (t.num_cat > 0) {
      t.cat_boundaries = ParseArray<int>(kv.Get("cat_boundaries"));
      t.cat_threshold = ParseArray<uint32_t>(kv.Get("cat_threshold"));
    }
    t.is_linear = std::stoi(kv.Get("is_linear", "0")) != 0;
    if (t.is_linear) {
      t.leaf_const = ParseArray<double>(kv.Get("leaf_const"));
      t.leaf_const.resize(t.num_leaves, 0.0);
      std::vector<int> counts = ParseArray<int>(kv.Get("num_features"));
      counts.resize(t.num_leaves, 0);
      std::vector<int> feats = ParseArray<int>(kv.Get("leaf_features"));
      std::vector<double> coefs = ParseArray<double>(kv.Get("leaf_coeff"));
      t.leaf_features.resize(t.num_leaves);
      t.leaf_coeff.resize(t.num_leaves);
      size_t pos = 0;
      for (int leaf = 0; leaf < t.num_leaves; ++leaf) {
        int c = counts[leaf];
        for (int j = 0; j < c && pos < feats.size(); ++j, ++pos) {
          t.leaf_features[leaf].push_back(feats[pos]);
          if (pos < coefs.size()) t.leaf_coeff[leaf].push_back(coefs[pos]);
        }
      }
    }
    return t;
  }
};

// ---------------------------------------------------------------------------
// booster
// ---------------------------------------------------------------------------

enum PredictType { kNormal = 0, kRawScore = 1, kLeafIndex = 2 };

struct Booster {
  int num_class = 1;
  int num_tree_per_iteration = 1;
  int max_feature_idx = 0;
  bool average_output = false;
  std::string objective = "regression";
  double sigmoid = 1.0;
  std::vector<Tree> trees;

  int NumIterations() const {
    return num_tree_per_iteration > 0
               ? static_cast<int>(trees.size()) / num_tree_per_iteration
               : 0;
  }

  // output transform — ObjectiveFunction::ConvertOutput analogs
  // (objectives.py convert_output; reference *_objective.hpp)
  void ConvertOutput(double* scores) const {
    if (objective == "binary" || objective == "multiclassova" ||
        objective == "xentropy" || objective == "cross_entropy") {
      for (int k = 0; k < num_class; ++k)
        scores[k] = 1.0 / (1.0 + std::exp(-sigmoid * scores[k]));
    } else if (objective == "multiclass" || objective == "softmax") {
      double mx = scores[0];
      for (int k = 1; k < num_class; ++k) mx = std::max(mx, scores[k]);
      double sum = 0.0;
      for (int k = 0; k < num_class; ++k) {
        scores[k] = std::exp(scores[k] - mx);
        sum += scores[k];
      }
      for (int k = 0; k < num_class; ++k) scores[k] /= sum;
    } else if (objective == "poisson" || objective == "gamma" ||
               objective == "tweedie") {
      for (int k = 0; k < num_class; ++k) scores[k] = std::exp(scores[k]);
    } else if (objective == "xentlambda" || objective == "cross_entropy_lambda") {
      for (int k = 0; k < num_class; ++k)
        scores[k] = std::log1p(std::exp(scores[k]));
    }
  }

  void PredictRow(const double* row, int t0, int t1, int type,
                  double* out) const {
    if (type == kLeafIndex) {
      for (int ti = t0; ti < t1; ++ti)
        out[ti - t0] = static_cast<double>(trees[ti].PredictLeaf(row));
      return;
    }
    for (int k = 0; k < num_class; ++k) out[k] = 0.0;
    for (int ti = t0; ti < t1; ++ti)
      out[ti % num_tree_per_iteration] += trees[ti].Predict(row);
    if (average_output && t1 > t0) {
      double inv = static_cast<double>(num_tree_per_iteration) / (t1 - t0);
      for (int k = 0; k < num_class; ++k) out[k] *= inv;
    }
    if (type == kNormal) ConvertOutput(out);
  }

  static Booster* FromString(const std::string& model, std::string* err) {
    size_t tree_pos = model.find("\nTree=");
    std::string header = model.substr(0, tree_pos == std::string::npos
                                             ? model.size() : tree_pos);
    KVBlock kv = ParseKV(header);
    if (!kv.Find("num_class") || !kv.Find("max_feature_idx")) {
      *err = "not a model file (missing num_class/max_feature_idx header)";
      return nullptr;
    }
    Booster* b = new Booster();
    b->num_class = std::stoi(kv.Get("num_class", "1"));
    b->num_tree_per_iteration =
        std::stoi(kv.Get("num_tree_per_iteration",
                         kv.Get("num_class", "1")));
    b->max_feature_idx = std::stoi(kv.Get("max_feature_idx", "0"));
    b->average_output = header.find("\naverage_output") != std::string::npos;
    std::istringstream obj(kv.Get("objective", "regression"));
    obj >> b->objective;
    std::string tok;
    while (obj >> tok) {
      size_t c = tok.find(':');
      if (c != std::string::npos && tok.substr(0, c) == "sigmoid")
        b->sigmoid = std::stod(tok.substr(c + 1));
    }
    // tree blocks: "Tree=i" ... up to next "Tree=" / "end of trees"
    size_t stop = model.find("\nend of trees");
    if (stop == std::string::npos) stop = model.size();
    size_t pos = tree_pos;
    while (pos != std::string::npos && pos < stop) {
      size_t start = pos + 1;
      size_t next = model.find("\nTree=", start);
      size_t end = next == std::string::npos ? stop : std::min(next, stop);
      b->trees.push_back(Tree::FromBlock(model.substr(start, end - start)));
      pos = next;
    }
    return b;
  }
};

int ResolveIterRange(const Booster* b, int start_iteration, int num_iteration,
                     int* t0, int* t1) {
  int k = b->num_tree_per_iteration;
  int total_iters = b->NumIterations();
  if (num_iteration <= 0) num_iteration = total_iters;
  *t0 = start_iteration * k;
  *t1 = std::min((start_iteration + num_iteration) * k,
                 static_cast<int>(b->trees.size()));
  if (*t0 > *t1) *t0 = *t1;
  return *t1 - *t0;
}

}  // namespace

// ---------------------------------------------------------------------------
// C API (c_api.h parity surface — prediction/model subset)
// ---------------------------------------------------------------------------

extern "C" {

typedef void* BoosterHandle;

const char* LGBM_GetLastError() { return g_last_error.c_str(); }

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  // malformed numeric fields (std::stoi/stod) must not let exceptions
  // escape the C ABI: report through LGBM_GetLastError like every other
  // failure path
  try {
    std::string err;
    Booster* b = Booster::FromString(model_str, &err);
    if (!b) return SetError(err);
    if (out_num_iterations) *out_num_iterations = b->NumIterations();
    *out = b;
    return 0;
  } catch (const std::exception& e) {
    return SetError(std::string("model parse error: ") + e.what());
  } catch (...) {
    return SetError("model parse error");
  }
}

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  std::ifstream f(filename, std::ios::binary);
  if (!f) return SetError(std::string("cannot open model file: ") + filename);
  std::stringstream ss;
  ss << f.rdbuf();
  std::string s = ss.str();
  return LGBM_BoosterLoadModelFromString(s.c_str(), out_num_iterations, out);
}

int LGBM_BoosterFree(BoosterHandle handle) {
  delete static_cast<Booster*>(handle);
  return 0;
}

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out) {
  *out = static_cast<Booster*>(handle)->num_class;
  return 0;
}

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out) {
  *out = static_cast<Booster*>(handle)->max_feature_idx + 1;
  return 0;
}

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle, int* out) {
  *out = static_cast<Booster*>(handle)->NumIterations();
  return 0;
}

// Dense row-major double matrix prediction.
// predict_type: 0 normal (transformed), 1 raw score, 2 leaf index.
// out_result: [nrow * num_class] for 0/1, [nrow * num_trees_used] for 2.
// out_len: number of doubles written.
int LGBM_BoosterPredictForMat(BoosterHandle handle, const double* data,
                              int32_t nrow, int32_t ncol, int predict_type,
                              int start_iteration, int num_iteration,
                              int64_t* out_len, double* out_result) {
  const Booster* b = static_cast<Booster*>(handle);
  if (ncol < b->max_feature_idx + 1)
    return SetError("ncol smaller than the model's feature count");
  int t0, t1;
  int used = ResolveIterRange(b, start_iteration, num_iteration, &t0, &t1);
  int width = predict_type == kLeafIndex ? used : b->num_class;
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int32_t i = 0; i < nrow; ++i)
    b->PredictRow(data + static_cast<int64_t>(i) * ncol, t0, t1, predict_type,
                  out_result + static_cast<int64_t>(i) * width);
  if (out_len) *out_len = static_cast<int64_t>(nrow) * width;
  return 0;
}

int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle,
                                       const double* data, int32_t ncol,
                                       int predict_type, int start_iteration,
                                       int num_iteration, int64_t* out_len,
                                       double* out_result) {
  return LGBM_BoosterPredictForMat(handle, data, 1, ncol, predict_type,
                                   start_iteration, num_iteration, out_len,
                                   out_result);
}

// CSR prediction (LGBM_BoosterPredictForCSR, c_api.h:815): each sparse
// row is densified into a per-thread scratch row (absent entries are 0.0,
// matching the reference's sparse missing-as-zero semantics) and pushed
// through the same tree walk.
int LGBM_BoosterPredictForCSR(BoosterHandle handle, const int32_t* indptr,
                              int64_t nindptr, const int32_t* indices,
                              const double* data, int64_t nelem, int64_t ncol,
                              int predict_type, int start_iteration,
                              int num_iteration, int64_t* out_len,
                              double* out_result) {
  const Booster* b = static_cast<Booster*>(handle);
  if (ncol < b->max_feature_idx + 1)
    return SetError("ncol smaller than the model's feature count");
  int t0, t1;
  int used = ResolveIterRange(b, start_iteration, num_iteration, &t0, &t1);
  int width = predict_type == kLeafIndex ? used : b->num_class;
  int64_t nrow = nindptr - 1;
#ifdef _OPENMP
#pragma omp parallel
#endif
  {
    std::vector<double> row(ncol, 0.0);
#ifdef _OPENMP
#pragma omp for schedule(static)
#endif
    for (int64_t i = 0; i < nrow; ++i) {
      // guard malformed CSR entries (index out of [0, ncol)) instead of
      // writing out of bounds — the reference predictor drops feature
      // indices past the model's range the same way
      for (int64_t e = indptr[i]; e < indptr[i + 1]; ++e)
        if (indices[e] >= 0 && indices[e] < ncol) row[indices[e]] = data[e];
      b->PredictRow(row.data(), t0, t1, predict_type, out_result + i * width);
      for (int64_t e = indptr[i]; e < indptr[i + 1]; ++e)
        if (indices[e] >= 0 && indices[e] < ncol) row[indices[e]] = 0.0;
    }
  }
  if (out_len) *out_len = nrow * width;
  return 0;
}

int LGBM_BoosterPredictForCSRSingleRow(BoosterHandle handle,
                                       const int32_t* indptr, int64_t nindptr,
                                       const int32_t* indices,
                                       const double* data, int64_t nelem,
                                       int64_t ncol, int predict_type,
                                       int start_iteration, int num_iteration,
                                       int64_t* out_len, double* out_result) {
  return LGBM_BoosterPredictForCSR(handle, indptr, nindptr, indices, data,
                                   nelem, ncol, predict_type, start_iteration,
                                   num_iteration, out_len, out_result);
}

// File prediction (LGBM_BoosterPredictForFile, c_api.h:749): CSV/TSV or
// LibSVM rows (detected by the presence of ':' pairs), results one
// prediction per line.  LibSVM indexing base is auto-detected by scanning
// the head of the file for a "0:" feature id (zero-based) — classic
// LibSVM / sklearn dump_svmlight_file emit one-based ids, which are
// shifted down by one; mirrors the Atof-based index probing the
// reference's parser does when choosing a parser.
// Scans the WHOLE file: zero-based is provable (a "0:" id somewhere),
// one-based only assumable — a zero-based file whose feature 0 is absent
// everywhere is indistinguishable from a one-based file missing its last
// feature (the same ambiguity sklearn's zero_based="auto" accepts).
// Per-token numeric conversion for dense rows: empty or unparsable text
// ("NA", "nan", "?", ...) maps to missing (NaN) instead of aborting the
// whole file — the reference parser's Atof treats unparsable fields as
// NaN the same way.
static double TokToDouble(const std::string& tok) {
  if (tok.empty()) return std::numeric_limits<double>::quiet_NaN();
  try {
    size_t used = 0;
    double v = std::stod(tok, &used);
    // trailing garbage ("12abc") is unparsable, not a number
    while (used < tok.size() &&
           (tok[used] == ' ' || tok[used] == '\r')) ++used;
    return used == tok.size() ? v
                              : std::numeric_limits<double>::quiet_NaN();
  } catch (const std::exception&) {
    return std::numeric_limits<double>::quiet_NaN();
  }
}

static int DetectLibsvmBase(std::ifstream* in) {
  std::string line;
  int base = 1;
  while (base == 1 && std::getline(*in, line)) {
    size_t sp = line.find_first_of(" \t");
    while (sp != std::string::npos) {
      size_t tok_end = line.find_first_of(" \t", sp + 1);
      std::string tok = line.substr(sp + 1, tok_end == std::string::npos
                                                 ? std::string::npos
                                                 : tok_end - sp - 1);
      size_t c = tok.find(':');
      if (c != std::string::npos && tok.substr(0, c) == "0") {
        base = 0;
        break;
      }
      sp = tok_end;
    }
  }
  in->clear();
  in->seekg(0);
  return base;
}

int LGBM_BoosterPredictForFile(BoosterHandle handle, const char* data_filename,
                               int data_has_header, int predict_type,
                               int start_iteration, int num_iteration,
                               const char* result_filename) {
  const Booster* b = static_cast<Booster*>(handle);
  std::ifstream in(data_filename);
  if (!in)
    return SetError(std::string("cannot open data file: ") + data_filename);
  // format is decided ONCE per file from the first data line (a LibSVM
  // row with zero feature pairs would otherwise fall into the CSV branch,
  // and a CSV field containing ':' into the LibSVM branch); the base scan
  // only runs for LibSVM input
  bool libsvm = false;
  {
    std::string probe;
    int skip = data_has_header ? 1 : 0;
    while (std::getline(in, probe)) {
      if (skip-- > 0 || probe.empty()) continue;
      libsvm = probe.find(':') != std::string::npos;
      break;
    }
    in.clear();
    in.seekg(0);
  }
  int svm_base = libsvm ? DetectLibsvmBase(&in) : 1;
  std::ofstream out(result_filename);
  if (!out)
    return SetError(std::string("cannot open result file: ") + result_filename);
  out.precision(17);
  int t0, t1;
  int used = ResolveIterRange(b, start_iteration, num_iteration, &t0, &t1);
  int width = predict_type == kLeafIndex ? used : b->num_class;
  int ncol = b->max_feature_idx + 1;
  std::vector<double> row(ncol), pred(width);
  std::string line;
  bool first = true;
  try {
    while (std::getline(in, line)) {
      if (!line.empty() && line.back() == '\r') line.pop_back();
      if (first && data_has_header) { first = false; continue; }
      first = false;
      if (line.empty()) continue;
      // dense rows: absent trailing fields are MISSING (NaN), matching
      // the reference parser; libsvm rows: absent features are sparse
      // zeros
      std::fill(row.begin(), row.end(),
                libsvm ? 0.0 : std::numeric_limits<double>::quiet_NaN());
      std::istringstream is(line);
      std::string tok;
      char sep = line.find('\t') != std::string::npos ? '\t' : ',';
      if (libsvm) {
        double label;  // leading label column, ignored
        is >> label;
        while (is >> tok) {
          size_t c = tok.find(':');
          if (c == std::string::npos) continue;
          int f = std::stoi(tok.substr(0, c)) - svm_base;
          if (f >= 0 && f < ncol) row[f] = std::stod(tok.substr(c + 1));
        }
      } else {
        // first column is the label (reference predict task convention
        // when label_column is default), remaining are features
        int col = -1;
        while (std::getline(is, tok, sep)) {
          if (col >= 0 && col < ncol) row[col] = TokToDouble(tok);
          ++col;
        }
      }
      b->PredictRow(row.data(), t0, t1, predict_type, pred.data());
      for (int k = 0; k < width; ++k)
        out << (k ? "\t" : "") << pred[k];
      out << "\n";
    }
  } catch (const std::exception& e) {
    return SetError(std::string("parse error in data file: ") + e.what());
  }
  return 0;
}

}  // extern "C"
