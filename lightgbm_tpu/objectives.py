"""Objective functions: per-row (gradient, hessian) computation on device.

Re-implements the reference objective family
(/root/reference/src/objective/*.hpp, factory objective_function.cpp:15-53)
as jitted JAX functions ``score -> (grad, hess)``.  Formulas follow the
reference exactly (including its non-textbook hessians, e.g. the constant
hessian of L1 and the 2*p*(1-p) multiclass-softmax hessian) so that trained
models are statistically equivalent.

Gradients for ranking objectives operate on padded per-query matrices
(static shapes for XLA) instead of the reference's per-query OpenMP loops
(rank_objective.hpp:25-95).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import Metadata


class ObjectiveFunction:
    """Base objective (include/LightGBM/objective_function.h analog)."""

    name = "custom"
    is_ranking = False
    num_model_per_iteration = 1
    need_renew_tree_output = False
    # True when get_gradients advances host-side state per call (e.g. a
    # host RNG counter): such objectives cannot be traced once and scanned
    # (the fused-chunk path would freeze one draw for all iterations)
    host_state_per_iter = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label, jnp.float32)
        w = metadata.weight
        self.weight = jnp.asarray(w, jnp.float32) if w is not None else None

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        """BoostFromScore: initial raw score (objective-specific average)."""
        return 0.0

    def convert_output(self, raw: jax.Array) -> jax.Array:
        return raw

    # leaf renewal (RenewTreeOutput) — objectives override when needed
    def renew_leaf_values(self, score: np.ndarray, leaf_of_row: np.ndarray,
                          num_leaves: int, leaf_values: np.ndarray) -> np.ndarray:
        return leaf_values

    def _apply_weight(self, grad, hess):
        if self.weight is not None:
            return grad * self.weight, hess * self.weight
        return grad, hess

    def _wmean(self, x: jax.Array) -> float:
        if self.weight is not None:
            return float(jnp.sum(x * self.weight) / jnp.sum(self.weight))
        return float(jnp.mean(x))


# ---------------------------------------------------------------------------
# regression (regression_objective.hpp)
# ---------------------------------------------------------------------------

class RegressionL2(ObjectiveFunction):
    name = "regression"

    def get_gradients(self, score):
        grad = score - self.label
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        if not self.config.boost_from_average:
            return 0.0
        return self._wmean(self.label)


class RegressionL1(ObjectiveFunction):
    name = "regression_l1"
    need_renew_tree_output = True

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        if not self.config.boost_from_average:
            return 0.0
        lbl = np.asarray(self.label)
        w = np.asarray(self.weight) if self.weight is not None else None
        return float(_weighted_percentile(lbl, w, 0.5))

    def renew_leaf_values(self, score, leaf_of_row, num_leaves, leaf_values):
        # RenewTreeOutput (regression_objective.hpp L1): leaf value = weighted
        # median of residuals in the leaf
        resid = np.asarray(self.label) - score
        w = np.asarray(self.weight) if self.weight is not None else None
        return _per_leaf_percentile(resid, w, leaf_of_row, num_leaves,
                                    leaf_values, 0.5)


class RegressionHuber(RegressionL2):
    name = "huber"

    def get_gradients(self, score):
        diff = score - self.label
        a = self.config.alpha
        grad = jnp.where(jnp.abs(diff) <= a, diff, a * jnp.sign(diff))
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)


class RegressionFair(ObjectiveFunction):
    name = "fair"

    def get_gradients(self, score):
        c = self.config.fair_c
        diff = score - self.label
        grad = c * diff / (jnp.abs(diff) + c)
        hess = c * c / (jnp.abs(diff) + c) ** 2
        return self._apply_weight(grad, hess)


class RegressionPoisson(ObjectiveFunction):
    name = "poisson"

    def get_gradients(self, score):
        # score is log-intensity (regression_objective.hpp PoissonLoss)
        grad = jnp.exp(score) - self.label
        hess = jnp.exp(score + self.config.poisson_max_delta_step)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        return float(np.log(max(self._wmean(self.label), 1e-20)))

    def convert_output(self, raw):
        return jnp.exp(raw)


class RegressionQuantile(ObjectiveFunction):
    name = "quantile"
    need_renew_tree_output = True

    def get_gradients(self, score):
        a = self.config.alpha
        delta = self.label - score
        grad = jnp.where(delta >= 0, -a, 1.0 - a)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self.label)
        w = np.asarray(self.weight) if self.weight is not None else None
        return float(_weighted_percentile(lbl, w, self.config.alpha))

    def renew_leaf_values(self, score, leaf_of_row, num_leaves, leaf_values):
        resid = np.asarray(self.label) - score
        w = np.asarray(self.weight) if self.weight is not None else None
        return _per_leaf_percentile(resid, w, leaf_of_row, num_leaves,
                                    leaf_values, self.config.alpha)


class RegressionMAPE(ObjectiveFunction):
    name = "mape"
    need_renew_tree_output = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.label_weight = 1.0 / jnp.maximum(jnp.abs(self.label), 1.0)

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff) * self.label_weight
        hess = self.label_weight
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self.label)
        w = np.asarray(self.label_weight)
        if self.weight is not None:
            w = w * np.asarray(self.weight)
        return float(_weighted_percentile(lbl, w, 0.5))

    def renew_leaf_values(self, score, leaf_of_row, num_leaves, leaf_values):
        resid = np.asarray(self.label) - score
        w = np.asarray(self.label_weight)
        if self.weight is not None:
            w = w * np.asarray(self.weight)
        return _per_leaf_percentile(resid, w, leaf_of_row, num_leaves,
                                    leaf_values, 0.5)


class RegressionGamma(ObjectiveFunction):
    name = "gamma"

    def get_gradients(self, score):
        # gamma deviance with log link
        grad = 1.0 - self.label * jnp.exp(-score)
        hess = self.label * jnp.exp(-score)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        return float(np.log(max(self._wmean(self.label), 1e-20)))

    def convert_output(self, raw):
        return jnp.exp(raw)


class RegressionTweedie(ObjectiveFunction):
    name = "tweedie"

    def get_gradients(self, score):
        rho = self.config.tweedie_variance_power
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        grad = -self.label * e1 + e2
        hess = -self.label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        return float(np.log(max(self._wmean(self.label), 1e-20)))

    def convert_output(self, raw):
        return jnp.exp(raw)


# ---------------------------------------------------------------------------
# binary (binary_objective.hpp:216)
# ---------------------------------------------------------------------------

class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = config.sigmoid

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        # reference positivity rule (binary_objective.hpp:37 is_pos_):
        # label > 0 is positive — {0, 10} labels train like {0, 1}
        lbl = (np.asarray(metadata.label) > 0).astype(np.float64)
        self.label = jnp.asarray(lbl, jnp.float32)
        cnt_pos = float(lbl.sum()) if metadata.weight is None else \
            float((lbl * metadata.weight).sum())
        cnt_neg = (float(len(lbl) - lbl.sum()) if metadata.weight is None else
                   float(((1 - lbl) * metadata.weight).sum()))
        self._cnt_pos, self._cnt_neg = cnt_pos, cnt_neg
        # is_unbalance / scale_pos_weight -> per-class label weights
        # (binary_objective.hpp:52-70)
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                self.label_weight = (1.0, cnt_pos / cnt_neg)
            else:
                self.label_weight = (cnt_neg / cnt_pos, 1.0)
        else:
            self.label_weight = (self.config.scale_pos_weight, 1.0)

    def get_gradients(self, score):
        y = self.label * 2.0 - 1.0          # {0,1} -> {-1,+1}
        sig = self.sigmoid
        wpos, wneg = self.label_weight
        lw = jnp.where(self.label > 0, wpos, wneg)
        response = -y * sig / (1.0 + jnp.exp(y * sig * score))
        grad = response * lw
        absr = jnp.abs(response)
        hess = absr * (sig - absr) * lw
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        if not self.config.boost_from_average:
            return 0.0
        wpos, wneg = self.label_weight
        pos, neg = self._cnt_pos * wpos, self._cnt_neg * wneg
        if pos <= 0 or neg <= 0:
            return 0.0
        pavg = pos / (pos + neg)
        return float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))


# ---------------------------------------------------------------------------
# multiclass (multiclass_objective.hpp:279)
# ---------------------------------------------------------------------------

class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label).astype(np.int32)
        if lbl.min() < 0 or lbl.max() >= self.num_class:
            raise ValueError("multiclass labels must be in [0, num_class)")
        self.onehot = jnp.asarray(np.eye(self.num_class, dtype=np.float32)[lbl])

    def get_gradients(self, score):
        # score: [N, K]
        p = jax.nn.softmax(score, axis=1)
        grad = p - self.onehot
        hess = 2.0 * p * (1.0 - p)   # factor-2 hessian (multiclass_objective.hpp)
        if self.weight is not None:
            return grad * self.weight[:, None], hess * self.weight[:, None]
        return grad, hess

    def boost_from_score(self, class_id=0):
        # log class prior (multiclass_objective.hpp:155
        # class_init_probs_) — softmax of the init scores reproduces
        # the empirical class distribution
        oh = np.asarray(self.onehot)
        w = np.asarray(self.weight)[:, None] if self.weight is not None \
            else 1.0
        probs = (oh * w).sum(axis=0)
        probs = probs / max(probs.sum(), 1e-15)
        return float(np.log(max(1e-15, probs[class_id])))

    def convert_output(self, raw):
        return jax.nn.softmax(raw, axis=-1)


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration = config.num_class
        self.sigmoid = config.sigmoid

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label).astype(np.int32)
        self.onehot = jnp.asarray(np.eye(self.num_class, dtype=np.float32)[lbl])

    def get_gradients(self, score):
        y = self.onehot * 2.0 - 1.0
        sig = self.sigmoid
        response = -y * sig / (1.0 + jnp.exp(y * sig * score))
        grad = response
        absr = jnp.abs(response)
        hess = absr * (sig - absr)
        if self.weight is not None:
            return grad * self.weight[:, None], hess * self.weight[:, None]
        return grad, hess

    def boost_from_score(self, class_id=0):
        # per-class binary boost (multiclass_objective.hpp:261 delegates
        # to the underlying binary losses)
        oh = np.asarray(self.onehot)
        w = np.asarray(self.weight) if self.weight is not None \
            else np.ones(len(oh))
        pos = float((oh[:, class_id] * w).sum())
        p = pos / max(float(w.sum()), 1e-15)
        if p <= 0.0 or p >= 1.0:
            return 0.0
        return float(np.log(p / (1.0 - p)) / self.sigmoid)

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * raw))


# ---------------------------------------------------------------------------
# cross entropy on [0,1] labels (xentropy_objective.hpp:283)
# ---------------------------------------------------------------------------

class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def get_gradients(self, score):
        p = jax.nn.sigmoid(score)
        grad = p - self.label
        hess = p * (1.0 - p)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id=0):
        pavg = min(max(self._wmean(self.label), 1e-9), 1 - 1e-9)
        return float(np.log(pavg / (1 - pavg)))

    def convert_output(self, raw):
        return jax.nn.sigmoid(raw)


class CrossEntropyLambda(ObjectiveFunction):
    """Bernoulli with complementary log-log parametrization
    (xentropy_objective.hpp CrossEntropyLambda)."""
    name = "cross_entropy_lambda"

    def get_gradients(self, score):
        # lambda = log1p(exp(score)); p = 1 - exp(-lambda*w)
        if self.weight is not None:
            w = self.weight
        else:
            w = jnp.ones_like(score)
        def loss(s, y, wi):
            lam = jax.nn.softplus(s)
            p = -jnp.expm1(-lam * wi)
            p = jnp.clip(p, 1e-12, 1 - 1e-12)
            return -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        g = jax.grad(loss, argnums=0)
        h = jax.grad(lambda s, y, wi: g(s, y, wi), argnums=0)
        grad = jax.vmap(g)(score, self.label, w)
        hess = jax.vmap(h)(score, self.label, w)
        return grad, jnp.maximum(hess, 1e-9)

    def boost_from_score(self, class_id=0):
        pavg = min(max(self._wmean(self.label), 1e-9), 1 - 1e-9)
        return float(np.log(np.expm1(-np.log1p(-pavg))))

    def convert_output(self, raw):
        return jax.nn.softplus(raw)


# ---------------------------------------------------------------------------
# ranking (rank_objective.hpp:366)
# ---------------------------------------------------------------------------

_RANK_BUCKETS = (16, 64, 256, 1024, 4096)


def _pad_queries(boundaries: np.ndarray):
    """Size-bucketed [Qb, mb] row-index/mask tensors from query boundaries
    — static-shape replacement for the per-query loops of
    RankingObjective::GetGradients (rank_objective.hpp:40-60).

    Queries are grouped by padded size (powers of 4, then one overflow
    bucket at the true max) so the pairwise [Qb, mb, mb] tensors track the
    ACTUAL work: padding every query to the global max would blow up on
    skewed query-size distributions (Yahoo LTR: thousands of ~20-doc
    queries plus a handful of 1000+-doc ones would cost Q x maxq^2).

    Returns a list of (query_ids [Qb], idx [Qb, mb], mask [Qb, mb], mb).
    """
    sizes = np.diff(boundaries)
    maxq = int(sizes.max())
    caps = [c for c in _RANK_BUCKETS if c < maxq] + [maxq]
    out = []
    for bi, cap in enumerate(caps):
        lo = 0 if bi == 0 else caps[bi - 1]
        qids = np.nonzero((sizes > lo) & (sizes <= cap))[0]
        if len(qids) == 0:
            continue
        idx = np.zeros((len(qids), cap), np.int32)
        mask = np.zeros((len(qids), cap), np.float32)
        for r, qi in enumerate(qids):
            s = sizes[qi]
            idx[r, :s] = np.arange(boundaries[qi], boundaries[qi + 1])
            mask[r, :s] = 1.0
        out.append((jnp.asarray(qids.astype(np.int32)), jnp.asarray(idx),
                    jnp.asarray(mask), int(cap)))
    return out


class LambdarankNDCG(ObjectiveFunction):
    """LambdaRank with NDCG deltas (rank_objective.hpp:97+ LambdarankNDCG).

    Pairwise lambdas weighted by |ΔNDCG| over padded per-query score
    matrices; sigmoid clamp and truncation level follow the reference.
    """
    name = "lambdarank"
    is_ranking = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("lambdarank requires query/group information")
        self.buckets = _pad_queries(metadata.query_boundaries)
        lg = self.config.label_gain
        max_label = int(np.asarray(metadata.label).max())
        if lg is None:
            lg = [(1 << i) - 1 for i in range(max_label + 2)]
        self.label_gain = jnp.asarray(lg, jnp.float32)
        self.trunc = int(self.config.lambdarank_truncation_level)
        self.norm = bool(self.config.lambdarank_norm)
        self.sigmoid = self.config.sigmoid
        # per-query inverse max DCG at truncation
        labels = np.asarray(metadata.label)
        b = metadata.query_boundaries
        inv = np.zeros(len(b) - 1, np.float32)
        gains = np.asarray(self.label_gain)
        for qi in range(len(b) - 1):
            ql = np.sort(labels[b[qi]:b[qi + 1]])[::-1][:self.trunc]
            dcg = (gains[ql.astype(np.int32)] /
                   np.log2(np.arange(2, len(ql) + 2))).sum()
            inv[qi] = 1.0 / dcg if dcg > 0 else 0.0
        self.inverse_max_dcg = jnp.asarray(inv)

        # one jitted kernel reused across buckets: jax re-traces per
        # distinct [Qb, mb] shape (a handful of compiles, bounded by
        # len(_RANK_BUCKETS)+1), each sized to its bucket's real work
        self._grad_fn = jax.jit(self._bucket_gradients)

    def _bucket_gradients(self, score, qidx, qmask, inv_dcg):
        s = score[qidx]                               # [Qb, M]
        y = self.label[qidx].astype(jnp.int32)
        neg = jnp.float32(-1e30)
        s_masked = jnp.where(qmask > 0, s, neg)
        # rank positions by descending score (ties by index, matching the
        # reference's stable argsort over scores)
        order = jnp.argsort(-s_masked, axis=1, stable=True)
        ranks = jnp.argsort(order, axis=1)            # pos of each doc
        gains = self.label_gain[y]                    # [Q, M]
        discount = 1.0 / jnp.log2(2.0 + ranks.astype(jnp.float32))
        in_trunc = ranks < self.trunc

        # pairwise [Q, M, M]
        si, sj = s[:, :, None], s[:, None, :]
        gi, gj = gains[:, :, None], gains[:, None, :]
        di, dj = discount[:, :, None], discount[:, None, :]
        valid = (qmask[:, :, None] * qmask[:, None, :]) > 0
        higher = gi > gj                              # i more relevant than j
        pair_trunc = in_trunc[:, :, None] | in_trunc[:, None, :]
        valid &= higher & pair_trunc

        delta = jnp.abs((gi - gj) * (di - dj)) * inv_dcg[:, None, None]
        if self.norm:
            # norm by |best - worst| proxy: reference normalizes lambdas by
            # sum; here scale deltas per query below
            pass
        sdiff = jnp.clip(self.sigmoid * (si - sj), -50.0, 50.0)
        p = 1.0 / (1.0 + jnp.exp(sdiff))              # P(i ranked below j)
        lam = self.sigmoid * p * delta
        hcoef = self.sigmoid * self.sigmoid * p * (1.0 - p) * delta
        lam = jnp.where(valid, lam, 0.0)
        hcoef = jnp.where(valid, hcoef, 0.0)

        grad_q = -lam.sum(axis=2) + lam.sum(axis=1)   # i gains, j loses
        hess_q = hcoef.sum(axis=2) + hcoef.sum(axis=1)
        if self.norm:
            # lambdarank_norm: normalize by total |lambda| per query
            tot = jnp.abs(lam).sum(axis=(1, 2)) + 1e-9
            cnt = qmask.sum(axis=1)
            scale = jnp.where(tot > 0, jnp.log2(1.0 + tot) / tot, 1.0)
            grad_q = grad_q * scale[:, None]
            hess_q = hess_q * scale[:, None]
            del cnt

        # scatter this bucket back to row space
        grad = jnp.zeros_like(score).at[qidx.reshape(-1)].add(
            (grad_q * qmask).reshape(-1))
        hess = jnp.zeros_like(score).at[qidx.reshape(-1)].add(
            (hess_q * qmask).reshape(-1))
        return grad, hess

    def get_gradients(self, score):
        if not hasattr(self, "_bucket_inv"):
            self._bucket_inv = [self.inverse_max_dcg[qids]
                                for qids, _, _, _ in self.buckets]
        grad = jnp.zeros_like(score)
        hess = jnp.zeros_like(score)
        for (qids, qidx, qmask, _mb), inv in zip(self.buckets,
                                                 self._bucket_inv):
            g, h = self._grad_fn(score, qidx, qmask, inv)
            grad = grad + g
            hess = hess + h
        return grad, jnp.maximum(hess, 1e-9)


class RankXENDCG(ObjectiveFunction):
    """Listwise XE-NDCG (rank_objective.hpp RankXENDCG): softmax ranking
    loss with per-iteration randomized relevance transform."""
    name = "rank_xendcg"
    is_ranking = True
    host_state_per_iter = True   # per-iteration gamma draw via host counter

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("rank_xendcg requires query/group information")
        self.buckets = _pad_queries(metadata.query_boundaries)
        self._key = jax.random.PRNGKey(self.config.objective_seed)
        self._iter = 0
        self._grad_fn = jax.jit(self._bucket_gradients)

    def _bucket_gradients(self, score, key, qids, qidx, qmask):
        s = jnp.where(qmask > 0, score[qidx], -1e30)
        y = self.label[qidx]
        # per-QUERY gamma stream keyed by global query id, so the draw a
        # query sees does not depend on how queries landed in buckets
        keys = jax.vmap(lambda q: jax.random.fold_in(key, q))(qids)
        gamma = jax.vmap(
            lambda k: jax.random.uniform(k, (qmask.shape[1],)))(keys)
        phi = (jnp.exp2(y) - gamma) * qmask
        target = phi / jnp.maximum(phi.sum(axis=1, keepdims=True), 1e-9)
        rho = jax.nn.softmax(s, axis=1) * qmask
        grad_q = (rho - target) * qmask
        hess_q = jnp.maximum(rho * (1.0 - rho), 1e-9) * qmask
        grad = jnp.zeros_like(score).at[qidx.reshape(-1)].add(grad_q.reshape(-1))
        hess = jnp.zeros_like(score).at[qidx.reshape(-1)].add(hess_q.reshape(-1))
        return grad, hess

    def get_gradients(self, score):
        self._iter += 1
        key = jax.random.fold_in(self._key, self._iter)
        grad = jnp.zeros_like(score)
        hess = jnp.zeros_like(score)
        for qids, qidx, qmask, _mb in self.buckets:
            g, h = self._grad_fn(score, key, qids, qidx, qmask)
            grad = grad + g
            hess = hess + h
        return grad, jnp.maximum(hess, 1e-9)


# ---------------------------------------------------------------------------
# helpers + factory
# ---------------------------------------------------------------------------

def _weighted_percentile(x: np.ndarray, w: Optional[np.ndarray], alpha: float) -> float:
    """Weighted percentile (PercentileFun/WeightedPercentileFun analog,
    regression_objective.hpp:30-80)."""
    if len(x) == 0:
        return 0.0
    order = np.argsort(x, kind="stable")
    xs = x[order]
    if w is None:
        # reference PercentileFun: position alpha*(n-1) with interpolation-free
        # upper selection
        pos = alpha * (len(xs) - 1)
        lo = int(np.floor(pos))
        hi = min(lo + 1, len(xs) - 1)
        frac = pos - lo
        return float(xs[lo] * (1 - frac) + xs[hi] * frac)
    ws = w[order]
    cum = np.cumsum(ws) - 0.5 * ws
    cum /= ws.sum()
    return float(np.interp(alpha, cum, xs))


def _per_leaf_percentile(resid: np.ndarray, w: Optional[np.ndarray],
                         leaf_of_row: np.ndarray, num_leaves: int,
                         leaf_values: np.ndarray, alpha: float) -> np.ndarray:
    out = leaf_values.copy()
    for leaf in range(num_leaves):
        m = leaf_of_row == leaf
        if m.any():
            out[leaf] = _weighted_percentile(resid[m], w[m] if w is not None else None,
                                             alpha)
    return out


_OBJECTIVES = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
}


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """Objective factory (objective_function.cpp:15-53).  ``custom`` returns
    None — gradients are then supplied by the caller (boosting.h:85)."""
    if config.objective == "custom":
        return None
    cls = _OBJECTIVES.get(config.objective)
    if cls is None:
        raise ValueError(f"Unknown objective: {config.objective}")
    return cls(config)
