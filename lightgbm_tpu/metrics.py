"""Evaluation metrics (reference: /root/reference/src/metric/*.hpp).

Host-side NumPy implementations — metrics run once per ``metric_freq``
iterations on score arrays pulled from device (the reference's metrics are
likewise CPU-side, metric.cpp:16-66 factory).  All support sample weights;
AUC / NDCG / MAP are rank-based O(n log n) like the reference.

Each metric reports ``(name, value, is_higher_better)``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import Metadata


class Metric:
    name = "metric"
    is_higher_better = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.label = np.asarray(metadata.label)
        self.weight = (np.asarray(metadata.weight)
                       if metadata.weight is not None else None)
        self.boundaries = metadata.query_boundaries
        self.num_data = num_data

    def _avg(self, per_row: np.ndarray) -> float:
        if self.weight is not None:
            return float(np.sum(per_row * self.weight) / np.sum(self.weight))
        return float(np.mean(per_row))

    def eval(self, score: np.ndarray) -> List[Tuple[str, float, bool]]:
        raise NotImplementedError


# ---- regression metrics (regression_metric.hpp:322) -----------------------

class _PointwiseMetric(Metric):
    def point(self, y, s):
        raise NotImplementedError

    def transform(self, s):
        return s

    def eval(self, score):
        s = self.transform(score)
        return [(self.name, self._avg(self.point(self.label, s)),
                 self.is_higher_better)]


class L2Metric(_PointwiseMetric):
    name = "l2"
    def point(self, y, s): return (y - s) ** 2


class RMSEMetric(_PointwiseMetric):
    name = "rmse"
    def point(self, y, s): return (y - s) ** 2
    def eval(self, score):
        return [(self.name, float(np.sqrt(self._avg(self.point(self.label, score)))),
                 False)]


class L1Metric(_PointwiseMetric):
    name = "l1"
    def point(self, y, s): return np.abs(y - s)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"
    def point(self, y, s):
        a = self.config.alpha
        d = y - s
        return np.where(d >= 0, a * d, (a - 1.0) * d)


class HuberMetric(_PointwiseMetric):
    name = "huber"
    def point(self, y, s):
        a = self.config.alpha
        d = np.abs(y - s)
        return np.where(d <= a, 0.5 * d * d, a * (d - 0.5 * a))


class FairMetric(_PointwiseMetric):
    name = "fair"
    def point(self, y, s):
        c = self.config.fair_c
        d = np.abs(y - s)
        return c * c * (d / c - np.log1p(d / c))


class PoissonMetric(_PointwiseMetric):
    name = "poisson"
    def transform(self, s): return np.exp(s)
    def point(self, y, s):
        eps = 1e-10
        return s - y * np.log(np.maximum(s, eps))


class MAPEMetric(_PointwiseMetric):
    name = "mape"
    def point(self, y, s):
        return np.abs(y - s) / np.maximum(np.abs(y), 1.0)


class GammaMetric(_PointwiseMetric):
    name = "gamma"
    def transform(self, s): return np.exp(s)
    def point(self, y, s):
        eps = 1e-10
        psi = y / np.maximum(s, eps)
        theta = -1.0 / np.maximum(s, eps)
        a = -np.log(-theta)
        return -np.log(np.maximum(y, eps)) - theta * y + a + psi * 0  # deviance core
    def eval(self, score):
        s = self.transform(score)
        eps = 1e-10
        ll = (self.label / np.maximum(s, eps) + np.log(np.maximum(s, eps)))
        return [(self.name, self._avg(ll), False)]


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma_deviance"
    def transform(self, s): return np.exp(s)
    def point(self, y, s):
        eps = 1e-10
        f = y / np.maximum(s, eps)
        return 2.0 * (np.log(np.maximum(1.0 / np.maximum(f, eps), eps)) + f - 1.0)


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"
    def transform(self, s): return np.exp(s)
    def point(self, y, s):
        rho = self.config.tweedie_variance_power
        eps = 1e-10
        s = np.maximum(s, eps)
        a = y * np.power(s, 1.0 - rho) / (1.0 - rho)
        b = np.power(s, 2.0 - rho) / (2.0 - rho)
        return -a + b


# ---- binary metrics (binary_metric.hpp:388) -------------------------------

def _sigmoid(x, k=1.0):
    return 1.0 / (1.0 + np.exp(-k * x))


class BinaryLoglossMetric(Metric):
    name = "binary_logloss"

    def eval(self, score):
        p = np.clip(_sigmoid(score, self.config.sigmoid), 1e-15, 1 - 1e-15)
        ll = -(self.label * np.log(p) + (1 - self.label) * np.log(1 - p))
        return [(self.name, self._avg(ll), False)]


class BinaryErrorMetric(Metric):
    name = "binary_error"

    def eval(self, score):
        pred = (score > 0).astype(np.float64)
        return [(self.name, self._avg((pred != self.label).astype(np.float64)),
                 False)]


def _auc(label: np.ndarray, score: np.ndarray,
         weight: Optional[np.ndarray]) -> float:
    """Rank-based weighted AUC (binary_metric.hpp AUCMetric, O(n log n))."""
    order = np.argsort(score, kind="mergesort")
    s, y = score[order], label[order]
    w = weight[order] if weight is not None else np.ones_like(y)
    # tie-aware: average rank within tied score groups
    pos_w = (y > 0) * w
    neg_w = (y <= 0) * w
    cum_neg = np.cumsum(neg_w)
    # group by unique score: within a tie group use half of the group's negatives
    _, first_idx, inv = np.unique(s, return_index=True, return_inverse=True)
    grp_neg = np.bincount(inv, weights=neg_w)
    cum_before = np.concatenate([[0.0], np.cumsum(grp_neg)[:-1]])
    rank_neg = cum_before[inv] + 0.5 * grp_neg[inv]
    area = float(np.sum(pos_w * rank_neg))
    tot_pos, tot_neg = float(pos_w.sum()), float(neg_w.sum())
    if tot_pos <= 0 or tot_neg <= 0:
        return 1.0
    return area / (tot_pos * tot_neg)


class AUCMetric(Metric):
    name = "auc"
    is_higher_better = True

    def eval(self, score):
        return [(self.name, _auc(self.label, score, self.weight), True)]


class AveragePrecisionMetric(Metric):
    name = "average_precision"
    is_higher_better = True

    def eval(self, score):
        order = np.argsort(-score, kind="mergesort")
        y = self.label[order]
        w = self.weight[order] if self.weight is not None else np.ones_like(y)
        tp = np.cumsum(y * w)
        all_ = np.cumsum(w)
        precision = tp / np.maximum(all_, 1e-15)
        ap = float(np.sum(precision * y * w) / max(np.sum(y * w), 1e-15))
        return [(self.name, ap, True)]


# ---- multiclass metrics (multiclass_metric.hpp:368) -----------------------

class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score):
        # score: [N, K] raw; softmax here
        s = score - score.max(axis=1, keepdims=True)
        p = np.exp(s)
        p /= p.sum(axis=1, keepdims=True)
        idx = self.label.astype(np.int64)
        ll = -np.log(np.clip(p[np.arange(len(idx)), idx], 1e-15, None))
        return [(self.name, self._avg(ll), False)]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score):
        k = self.config.multi_error_top_k
        idx = self.label.astype(np.int64)
        true_score = score[np.arange(len(idx)), idx]
        rank = (score >= true_score[:, None]).sum(axis=1)
        err = (rank > k).astype(np.float64)
        # top-k > 1 reports as multi_error@k (multiclass_metric.hpp
        # MultiErrorMetric::Name)
        name = self.name if k <= 1 else f"{self.name}@{k}"
        return [(name, self._avg(err), False)]


class AucMuMetric(Metric):
    """auc_mu (multiclass_metric.hpp auc_mu): mean pairwise-class AUC."""
    name = "auc_mu"
    is_higher_better = True

    def eval(self, score):
        k = score.shape[1]
        idx = self.label.astype(np.int64)
        aucs = []
        for a in range(k):
            for b in range(a + 1, k):
                m = (idx == a) | (idx == b)
                if not m.any():
                    continue
                y = (idx[m] == a).astype(np.float64)
                s = score[m, a] - score[m, b]
                w = self.weight[m] if self.weight is not None else None
                aucs.append(_auc(y, s, w))
        return [(self.name, float(np.mean(aucs)) if aucs else 1.0, True)]


# ---- ranking metrics (rank_metric.hpp:169, dcg_calculator.cpp) ------------

class NDCGMetric(Metric):
    name = "ndcg"
    is_higher_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lg = self.config.label_gain
        max_label = int(self.label.max()) if len(self.label) else 0
        if lg is None:
            lg = [(1 << i) - 1 for i in range(max_label + 2)]
        self.label_gain = np.asarray(lg, np.float64)

    def eval(self, score):
        if self.boundaries is None:
            raise ValueError("ndcg metric requires query information")
        eval_at = [int(k) for k in self.config.eval_at]
        b = self.boundaries
        sums = np.zeros(len(eval_at))
        cnt = 0
        for qi in range(len(b) - 1):
            y = self.label[b[qi]:b[qi + 1]].astype(np.int64)
            s = score[b[qi]:b[qi + 1]]
            order = np.argsort(-s, kind="mergesort")
            ideal = np.sort(y)[::-1]
            cnt += 1
            for j, k in enumerate(eval_at):
                kk = min(k, len(y))
                disc = 1.0 / np.log2(np.arange(2, kk + 2))
                dcg = float((self.label_gain[y[order[:kk]]] * disc).sum())
                idcg = float((self.label_gain[ideal[:kk]] * disc).sum())
                sums[j] += dcg / idcg if idcg > 0 else 1.0
        return [(f"ndcg@{k}", sums[j] / max(cnt, 1), True)
                for j, k in enumerate(eval_at)]


class MAPMetric(Metric):
    name = "map"
    is_higher_better = True

    def eval(self, score):
        if self.boundaries is None:
            raise ValueError("map metric requires query information")
        eval_at = [int(k) for k in self.config.eval_at]
        b = self.boundaries
        sums = np.zeros(len(eval_at))
        cnt = 0
        for qi in range(len(b) - 1):
            y = (self.label[b[qi]:b[qi + 1]] > 0).astype(np.float64)
            s = score[b[qi]:b[qi + 1]]
            order = np.argsort(-s, kind="mergesort")
            ys = y[order]
            cnt += 1
            hits = np.cumsum(ys)
            prec = hits / np.arange(1, len(ys) + 1)
            for j, k in enumerate(eval_at):
                kk = min(k, len(ys))
                npos = ys[:kk].sum()
                sums[j] += (prec[:kk] * ys[:kk]).sum() / npos if npos > 0 else 0.0
        return [(f"map@{k}", sums[j] / max(cnt, 1), True)
                for j, k in enumerate(eval_at)]


# ---- cross-entropy metrics (xentropy_metric.hpp:358) ----------------------

class CrossEntropyMetric(Metric):
    name = "cross_entropy"

    def eval(self, score):
        p = np.clip(_sigmoid(score), 1e-15, 1 - 1e-15)
        ll = -(self.label * np.log(p) + (1 - self.label) * np.log(1 - p))
        return [(self.name, self._avg(ll), False)]


class CrossEntropyLambdaMetric(Metric):
    name = "cross_entropy_lambda"

    def eval(self, score):
        lam = np.log1p(np.exp(score))
        p = np.clip(-np.expm1(-lam), 1e-15, 1 - 1e-15)
        ll = -(self.label * np.log(p) + (1 - self.label) * np.log(1 - p))
        return [(self.name, self._avg(ll), False)]


class KLDivMetric(Metric):
    name = "kldiv"

    def eval(self, score):
        p = np.clip(_sigmoid(score), 1e-15, 1 - 1e-15)
        y = np.clip(self.label, 1e-15, 1 - 1e-15)
        kl = (y * np.log(y / p) + (1 - y) * np.log((1 - y) / (1 - p)))
        return [(self.name, self._avg(kl), False)]


# ---- traced (jit-able) metric forms ---------------------------------------
#
# Device-resident evaluation for the super-epoch trainer
# (models/gbdt.py train_superepoch) and the booster's fused_eval path:
# each factory returns a pure ``(score, label, weight) -> f32 scalar``
# that jits into the training scan (or a standalone eval program) over
# PADDED valid buckets.  Padding rows carry weight 0.0, so every traced
# metric is a weighted mean/ratio that ignores them by construction —
# the caller always passes a weight vector (ones where the user gave
# none, zeros on the pad tail).  Metrics without a traced form return
# None from traced_metric_fn, which gates the engine back to the
# per-iteration host path.  Traced values are f32 (the host metrics
# compute in f64): the byte-identity contract is traced-vs-traced
# (superepoch vs fused_eval="true" per-iteration — docs/Fused-
# Training.md), while the clip floor is widened to 1e-7 because
# ``1 - 1e-15`` rounds to 1.0 in f32 and would emit inf on saturated
# scores.

def _t_wavg(per_row, w):
    return jnp.sum(per_row * w) / jnp.sum(w)


def _t_binary_logloss(config: Config):
    sig = float(config.sigmoid)

    def fn(score, label, weight):
        p = 1.0 / (1.0 + jnp.exp(-sig * score))
        p = jnp.clip(p, 1e-7, 1.0 - 1e-7)
        ll = -(label * jnp.log(p) + (1.0 - label) * jnp.log(1.0 - p))
        return _t_wavg(ll, weight)
    return fn


def _t_auc(config: Config):
    # exact tie-aware weighted AUC, the _auc() recurrence in traced
    # form: stable ascending sort, tie groups via a cumsum of
    # score-change flags, per-group negative mass via segment_sum —
    # pad rows have weight 0 so joining a tie group changes nothing
    def fn(score, label, weight):
        order = jnp.argsort(score, stable=True)
        s, y, w = score[order], label[order], weight[order]
        pos_w = jnp.where(y > 0, w, 0.0)
        neg_w = jnp.where(y <= 0, w, 0.0)
        newgrp = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             (s[1:] != s[:-1]).astype(jnp.int32)])
        gid = jnp.cumsum(newgrp)
        grp_neg = jax.ops.segment_sum(neg_w, gid,
                                      num_segments=s.shape[0])
        cum_before = jnp.cumsum(grp_neg) - grp_neg
        rank_neg = cum_before[gid] + 0.5 * grp_neg[gid]
        area = jnp.sum(pos_w * rank_neg)
        tp, tn = jnp.sum(pos_w), jnp.sum(neg_w)
        return jnp.where((tp > 0) & (tn > 0), area / (tp * tn),
                         jnp.float32(1.0))
    return fn


def _t_l2(config: Config):
    def fn(score, label, weight):
        return _t_wavg((label - score) ** 2, weight)
    return fn


def _t_rmse(config: Config):
    def fn(score, label, weight):
        return jnp.sqrt(_t_wavg((label - score) ** 2, weight))
    return fn


def _t_l1(config: Config):
    def fn(score, label, weight):
        return _t_wavg(jnp.abs(label - score), weight)
    return fn


def _t_multi_logloss(config: Config):
    # score: [N, K] raw — parity partner for MultiLoglossMetric; the
    # scan path never reaches it (num_class > 1 is unfusable) but the
    # fused_eval="true" per-iteration path does
    def fn(score, label, weight):
        s = score - jnp.max(score, axis=1, keepdims=True)
        p = jnp.exp(s)
        p = p / jnp.sum(p, axis=1, keepdims=True)
        idx = label.astype(jnp.int32)
        picked = jnp.take_along_axis(p, idx[:, None], axis=1)[:, 0]
        ll = -jnp.log(jnp.clip(picked, 1e-7, None))
        return _t_wavg(ll, weight)
    return fn


_TRACED_METRICS: Dict[str, Callable[[Config], Callable]] = {
    "binary_logloss": _t_binary_logloss,
    "auc": _t_auc,
    "l2": _t_l2,
    "rmse": _t_rmse,
    "l1": _t_l1,
    "multi_logloss": _t_multi_logloss,
}


def traced_metric_fn(name: str, config: Config) -> Optional[Callable]:
    """Jit-able ``(score, label, weight) -> f32 scalar`` for ``name``,
    or None when the metric has no traced form (engine falls back to
    per-iteration host eval)."""
    mk = _TRACED_METRICS.get(name)
    return mk(config) if mk is not None else None


def build_traced_eval(eval_spec: Sequence[Tuple],
                      config: Config) -> Optional[Callable]:
    """The ONE jitted eval program both fused paths report through.

    ``eval_spec`` is a tuple of ``(valid_idx, set_name, metric_name,
    higher_better)`` entries in ``booster.eval_valid()`` order; the
    returned ``teval(svecs, ops)`` evaluates every entry over device
    score VECTORS (``svecs[vi]``: f32 ``[rows]``) and padded
    ``(label, weight)`` pairs (``ops[vi]``), returning an f32 ``[E]``
    stack.  Returns None when any metric lacks a traced form.

    Why a shared program instead of evaluating inside the training
    scan: XLA may fuse a reduction differently depending on the
    surrounding program, and different fusion can round the last ulp
    differently even on bitwise-identical inputs.  The super-epoch
    trainer therefore evaluates its in-scan metrics only to drive the
    early-stop VOTE, and recomputes the REPORTED values post-scan
    through this program — the same one ``fused_eval="true"``
    per-iteration runs use — so record_evals are bit-identical across
    the two paths by construction (docs/Fused-Training.md)."""
    spec = tuple(eval_spec)
    fns = tuple(traced_metric_fn(mn, config)
                for (_vi, _n, mn, _h) in spec)
    if any(f is None for f in fns):
        return None
    from .obs.flops import eval_flops_bytes, note_traced
    from .utils.compile_cache import trace_event

    @jax.jit
    def teval(svecs, ops):
        trace_event("traced_eval")
        if not spec:
            return jnp.zeros((0,), jnp.float32)
        note_traced("fused_eval",
                    *eval_flops_bytes(
                        sum(int(s.shape[0]) for s in svecs)
                        // max(len(svecs), 1), len(spec)),
                    phase="eval", cadence="iter")
        return jnp.stack([
            f(svecs[vi], ops[vi][0], ops[vi][1])
            for f, (vi, _n, _mn, _h) in zip(fns, spec)])
    return teval


_METRICS = {
    "l1": L1Metric, "l2": L2Metric, "rmse": RMSEMetric,
    "quantile": QuantileMetric, "huber": HuberMetric, "fair": FairMetric,
    "poisson": PoissonMetric, "mape": MAPEMetric, "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary_error": BinaryErrorMetric,
    "auc": AUCMetric, "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric, "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
    "ndcg": NDCGMetric, "map": MAPMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kldiv": KLDivMetric,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """Metric factory (metric.cpp:16-66)."""
    if name in ("custom", "none", ""):
        return None
    cls = _METRICS.get(name)
    if cls is None:
        raise ValueError(f"Unknown metric: {name}")
    return cls(config)
