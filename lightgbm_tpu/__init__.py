"""lightgbm_tpu: a TPU-native gradient-boosting framework.

A from-scratch JAX/XLA/Pallas re-design of the LightGBM GBDT framework
(reference: /root/reference) for TPU hardware: the tree learner is a fully
device-resident jitted program (histograms on the MXU, vectorized split
scans, row->leaf partition vector), distributed training uses XLA
collectives over a `jax.sharding.Mesh`, and the Python API mirrors the
reference's (`Dataset`, `Booster`, `train`, `cv`, sklearn wrappers).
"""

__version__ = "0.1.0"

from .basic import LightGBMError
from .binning import BinMapper, BinType, MissingType
from .booster import Booster
from .callback import (EarlyStopException, early_stopping, log_evaluation,
                       log_telemetry, record_evaluation, reset_parameter)
from .config import Config
from .dataset import Dataset, Sequence
from .engine import CVBooster, cv, train
from .fleet import FleetResult, fleet_train
from .ingest import IngestRunner, ingest_dataset
from .pipeline import ContinualTrainer, GateFailure
from .plotting import (create_tree_digraph, plot_importance, plot_metric,
                       plot_split_value_histogram, plot_tree)
from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor
from .utils.log import register_logger

__all__ = [
    "BinMapper", "BinType", "MissingType", "Booster", "Config",
    "ContinualTrainer", "CVBooster",
    "Dataset", "EarlyStopException", "GateFailure", "IngestRunner",
    "FleetResult", "LightGBMError", "Sequence", "cv", "fleet_train",
    "ingest_dataset",
    "early_stopping", "log_evaluation", "log_telemetry",
    "record_evaluation", "reset_parameter", "train",
    "LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
    "DaskLGBMRegressor", "DaskLGBMClassifier", "DaskLGBMRanker",
    "register_logger",
    "plot_importance", "plot_split_value_histogram", "plot_metric",
    "plot_tree", "create_tree_digraph",
]

_DASK_TO_DIST = {
    "DaskLGBMRegressor": "DistributedLGBMRegressor",
    "DaskLGBMClassifier": "DistributedLGBMClassifier",
    "DaskLGBMRanker": "DistributedLGBMRanker",
}


def __getattr__(name: str):
    # the reference exports Dask estimators from the top level; the
    # Distributed* estimators are their analog here (distributed.py) and
    # answer to BOTH spellings — resolved lazily so importing the
    # package doesn't pay for the orchestration module
    if name in _DASK_TO_DIST or name.startswith("DistributedLGBM"):
        from . import distributed
        return getattr(distributed, _DASK_TO_DIST.get(name, name))
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
