"""DART boosting (reference: /root/reference/src/boosting/dart.hpp:20-211).

Dropout trees: each iteration a random subset of existing trees is dropped
(``DroppingTrees``), gradients are computed against the score without them
(``GetTrainingScore`` override, dart.hpp:74-85), and after the new tree is
added both it and the dropped trees are re-normalized (``Normalize``):
standard mode scales the new tree by 1/(k+1) and dropped trees by k/(k+1);
xgboost_dart_mode uses lr/(k+lr) and k/(k+lr).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from .gbdt import GBDTModel
from ..predict_device import add_tree_score


class DARTModel(GBDTModel):
    def __init__(self, config, train_set, objective, hist_reduce=None):
        super().__init__(config, train_set, objective, hist_reduce)
        self._rng_drop = np.random.RandomState(config.drop_seed)
        self._drop_idx: List[int] = []
        self._drop_contrib_train = None     # [N, K] score of dropped trees
        self._drop_contrib_valid = []

    def _select_drop(self) -> List[int]:
        n_trees = len(self.device_trees) // self.num_class
        if n_trees == 0 or self._rng_drop.rand() < self.config.skip_drop:
            return []
        rate = self.config.drop_rate
        if self.config.uniform_drop:
            mask = self._rng_drop.rand(n_trees) < rate
        else:
            w = np.asarray(self.tree_weights[::self.num_class])
            p = np.clip(rate * w * n_trees / max(w.sum(), 1e-12), 0, 1)
            mask = self._rng_drop.rand(n_trees) < p
        drop = list(np.nonzero(mask)[0])
        if len(drop) > self.config.max_drop > 0:
            drop = list(self._rng_drop.choice(drop, self.config.max_drop,
                                              replace=False))
        return sorted(drop)

    def _tree_contrib(self, binned, ti: int, k: int):
        from .gbdt import _apply_tree
        dt = self.device_trees[ti * self.num_class + k]
        w = self.tree_weights[ti * self.num_class + k]
        zero = jnp.zeros(binned.shape[0], jnp.float32)
        return _apply_tree(zero, binned, dt, self.na_bin_dev, w,
                           self.efb_maps)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        self._drop_idx = self._select_drop()
        k_drop = len(self._drop_idx)
        if k_drop > 0:
            contrib = jnp.zeros_like(self.score)
            for ti in self._drop_idx:
                for k in range(self.num_class):
                    contrib = contrib.at[:, k].add(
                        self._tree_contrib(self.binned_dev, ti, k))
            self._drop_contrib_train = contrib
            self._drop_contrib_valid = []
            for (vds, vbinned, _vs) in self.valid_sets:
                # zeros_like: the valid score may carry row-bucket
                # padding (gbdt.add_valid_set), so size off the score
                vc = jnp.zeros_like(_vs)
                for ti in self._drop_idx:
                    for k in range(self.num_class):
                        vc = vc.at[:, k].add(self._tree_contrib(vbinned, ti, k))
                self._drop_contrib_valid.append(vc)
            # drop: gradients see score minus dropped trees
            self.score = self.score - contrib
            for vi in range(len(self.valid_sets)):
                vds, vb, vs = self.valid_sets[vi]
                self.valid_sets[vi] = (vds, vb, vs - self._drop_contrib_valid[vi])

        stopped = super().train_one_iter(grad, hess)

        # Normalize (dart.hpp:120-170)
        if k_drop > 0:
            lr = self.learning_rate
            if self.config.xgboost_dart_mode:
                new_factor = lr / (k_drop + lr)
                old_factor = k_drop / (k_drop + lr)
            else:
                new_factor = 1.0 / (k_drop + 1.0)
                old_factor = k_drop / (k_drop + 1.0)
            # scale the just-added trees
            for k in range(self.num_class):
                ti = len(self.tree_weights) - self.num_class + k
                self.tree_weights[ti] *= new_factor
                st = self._last_iter_state
                delta = jnp.take(st["leaf_values"][k], st["leaf_of_rows"][k])
                self.score = self.score.at[:, k].add((new_factor - 1.0) * delta)
                for vi in range(len(self.valid_sets)):
                    vds, vb, vs = self.valid_sets[vi]
                    dt = st["trees"][k]
                    from .gbdt import _apply_tree
                    ns = _apply_tree(vs[:, k], vb, dt, self.na_bin_dev,
                                     new_factor - 1.0, self.efb_maps)
                    self.valid_sets[vi] = (vds, vb, vs.at[:, k].set(ns))
            # scale dropped trees and restore their (rescaled) contribution
            for ti in self._drop_idx:
                for k in range(self.num_class):
                    self.tree_weights[ti * self.num_class + k] *= old_factor
            self.score = self.score + self._drop_contrib_train * old_factor
            for vi in range(len(self.valid_sets)):
                vds, vb, vs = self.valid_sets[vi]
                self.valid_sets[vi] = (
                    vds, vb, vs + self._drop_contrib_valid[vi] * old_factor)
            self._drop_contrib_train = None
            self._drop_contrib_valid = []
        return stopped
