"""Super-epoch training parity (GBDTModel.train_superepoch).

The whole-run on-device path — ``lax.scan`` over k FULL boosting
iterations with in-scan valid scoring, traced eval and the early-stop
vote, ONE host fetch per epoch — must be byte-identical to the
per-iteration path: same trees, same ``best_iteration``, same
``record_evals`` values (the per-iteration twin evaluates through the
SAME jitted program via ``fused_eval=true`` — metrics.build_traced_eval;
the host f64 metrics are a different contract by construction).
"""

import glob
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.gbdt import GBDTModel

BASE = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
        "max_bin": 31, "min_data_in_leaf": 5, "verbosity": -1,
        "tpu_learner": "masked", "metric": ["binary_logloss", "auc"]}

# param lines that legitimately differ between the two paths' saved
# parameter sections (the trees must still match byte-for-byte)
_PATH_PARAMS = ("[superepoch:", "[fused_eval:", "[fused_chunk:")


def _norm(model_str):
    return "\n".join(l for l in model_str.splitlines()
                     if not l.startswith(_PATH_PARAMS))


def _data(n=2400, f=12, seed=7, n_class=1):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    raw = x[:, 0] - 0.5 * x[:, 1] + 0.4 * x[:, 2] * x[:, 3] \
        + 0.3 * rng.randn(n)
    if n_class > 1:
        y = (np.digitize(raw, np.quantile(raw, [0.33, 0.66]))
             .astype(np.float32))
    elif BASE["objective"] == "binary":
        y = (raw > 0).astype(np.float32)
    else:
        y = raw.astype(np.float32)
    return x, y


def _run(params, rounds=40, n_valid=1, es=5, seed=7, n_class=1,
         binary=True):
    """Train with ``n_valid`` valid sets and (optionally) an
    early-stopping callback; returns (booster, record, n_superepochs)."""
    calls = [0]
    orig = GBDTModel.train_superepoch

    def spy(self, *a, **k):
        calls[0] += 1
        return orig(self, *a, **k)

    x, y = _data(seed=seed, n_class=n_class)
    if not binary:
        y = (x[:, 0] - 0.5 * x[:, 1] + 0.3
             * np.random.RandomState(seed).randn(len(y))).astype(
                 np.float32)
    dtr = lgb.Dataset(x[:1600], label=y[:1600])
    vs, vn = [], []
    for vi in range(n_valid):
        lo = 1600 + 400 * vi
        vs.append(lgb.Dataset(x[lo:lo + 400], label=y[lo:lo + 400],
                              reference=dtr))
        vn.append(f"v{vi}")
    rec = {}
    # always present: a replay-safe callback keeps the plain fused-chunk
    # loop out of the way so the super-epoch path is what's exercised
    cbs = [lgb.record_evaluation(rec)]
    if es and n_valid:
        cbs.append(lgb.early_stopping(es, verbose=False))
    GBDTModel.train_superepoch = spy
    try:
        bst = lgb.train(dict(params), dtr, num_boost_round=rounds,
                        valid_sets=vs, valid_names=vn, callbacks=cbs)
    finally:
        GBDTModel.train_superepoch = orig
    return bst, rec, calls[0]


def _assert_identical(pa, pb, **kw):
    ba, ra, na = _run(pa, **kw)
    bb, rb, nb = _run(pb, **kw)
    assert nb == 0, "reference run must not take the super-epoch path"
    assert ba.best_iteration == bb.best_iteration
    assert ra == rb                       # exact float equality, nested
    assert _norm(ba.model_to_string()) == _norm(bb.model_to_string())
    assert ba.best_score == bb.best_score
    return na


MATRIX = {
    "binary_es": ({}, dict(es=5, n_valid=1)),
    "binary_no_es": ({}, dict(es=0, n_valid=1)),
    "binary_two_valids": ({}, dict(es=5, n_valid=2)),
    "binary_quant_int8": ({"quant_train": True, "quant_bits": 8},
                          dict(es=0, n_valid=1)),
    "binary_bagging": ({"bagging_freq": 2, "bagging_fraction": 0.7},
                       dict(es=5, n_valid=1)),
    "regression_es": ({"objective": "regression", "metric": ["l2"]},
                      dict(es=5, n_valid=1, binary=False)),
    "regression_l1_rmse": ({"objective": "regression",
                            "metric": ["rmse", "l1"]},
                           dict(es=0, n_valid=1, binary=False)),
}


@pytest.mark.parametrize("name", list(MATRIX))
def test_superepoch_byte_identity(name):
    extra, kw = MATRIX[name]
    pa = dict(BASE, fused_chunk=8, **extra)
    pb = dict(BASE, fused_chunk=8, superepoch=-1, fused_eval="true",
              **extra)
    n_epochs = _assert_identical(pa, pb, **kw)
    assert n_epochs >= 1, "super-epoch path must actually engage"


def test_superepoch_explicit_k():
    # explicit superepoch overrides the auto (fused_chunk / ES) sizing
    pa = dict(BASE, fused_chunk=0, superepoch=16)
    pb = dict(BASE, fused_chunk=0, superepoch=-1, fused_eval="true")
    n_epochs = _assert_identical(pa, pb, es=0, n_valid=1, rounds=32)
    assert n_epochs == 2


def test_superepoch_no_valid_sets():
    # no valid sets + a replayable callback: epochs run with an empty
    # eval_spec (the plain fused-chunk loop is blocked by the callback)
    pa = dict(BASE, fused_chunk=8)
    pb = dict(BASE, fused_chunk=0, superepoch=-1)
    ba, _, na = _run(pa, es=0, n_valid=0, rounds=24)
    bb, _, nb = _run(pb, es=0, n_valid=0, rounds=24)
    assert na >= 1 and nb == 0
    assert _norm(ba.model_to_string()) == _norm(bb.model_to_string())


def test_superepoch_multiclass_falls_back():
    # num_class > 1 is unfusable: the plan must decline (fused_reasons
    # names the blocker) and the per-iteration fallback still matches
    # a plain per-iteration run exactly
    extra = {"objective": "multiclass", "num_class": 3,
             "metric": ["multi_logloss"]}
    pa = dict(BASE, fused_chunk=8, **extra)
    pb = dict(BASE, fused_chunk=0, superepoch=-1, **extra)
    ba, ra, na = _run(pa, es=5, n_valid=1, rounds=20, n_class=3)
    bb, rb, nb = _run(pb, es=5, n_valid=1, rounds=20, n_class=3)
    assert na == 0 and nb == 0
    assert ba.best_iteration == bb.best_iteration
    assert ra == rb
    assert _norm(ba.model_to_string()) == _norm(bb.model_to_string())


def test_superepoch_one_sync_per_epoch():
    # the acceptance pin: with a valid set AND early stopping active,
    # a super-epoch issues exactly ONE jax.device_get per epoch (the
    # fused_fetch in GBDTModel._eget) — 32 rounds / k=8 -> 4 epochs,
    # 4 device_gets, nothing else in the training loop syncs
    import jax
    x, y = _data()
    dtr = lgb.Dataset(x[:1600], label=y[:1600])
    dva = lgb.Dataset(x[1600:2000], label=y[1600:2000], reference=dtr)
    # construct up front so binning/bring-up work is outside the count
    dtr.construct()
    dva.construct()
    count = [0]
    orig = jax.device_get

    def counting(v):
        count[0] += 1
        return orig(v)

    p = dict(BASE, fused_chunk=8)
    jax.device_get = counting
    try:
        bst = lgb.train(p, dtr, num_boost_round=32, valid_sets=[dva],
                        valid_names=["va"],
                        callbacks=[lgb.early_stopping(50, verbose=False)])
    finally:
        jax.device_get = orig
    assert len(bst.trees) == 32
    assert count[0] == 4, \
        f"expected 1 host sync per epoch (4 epochs), got {count[0]}"


def test_superepoch_kill_resume_at_epoch_boundary(tmp_path):
    # epoch sizing clips to the snapshot boundary, so a crash+resume at
    # an epoch edge reproduces the straight run byte-for-byte
    out = str(tmp_path / "m.txt")
    p = dict(BASE, fused_chunk=8, snapshot_freq=8, output_model=out)
    x, y = _data()
    dtr = lgb.Dataset(x[:1600], label=y[:1600])
    dva = lgb.Dataset(x[1600:2000], label=y[1600:2000], reference=dtr)

    def mk():
        d = lgb.Dataset(x[:1600], label=y[:1600])
        v = lgb.Dataset(x[1600:2000], label=y[1600:2000], reference=d)
        return d, [v]

    d0, v0 = mk()
    straight = lgb.train(dict(p), d0, num_boost_round=24, valid_sets=v0,
                         valid_names=["va"],
                         callbacks=[lgb.record_evaluation({})])
    s_straight = straight.model_to_string()
    for f in glob.glob(out + "*"):
        os.unlink(f)

    # "crash" after 16 of 24 rounds (two full epochs, snapshot at 16)
    d1, v1 = mk()
    lgb.train(dict(p), d1, num_boost_round=16, valid_sets=v1,
              valid_names=["va"], callbacks=[lgb.record_evaluation({})])
    d2, v2 = mk()
    resumed = lgb.train(dict(p, resume=True), d2, num_boost_round=24,
                        valid_sets=v2, valid_names=["va"],
                        callbacks=[lgb.record_evaluation({})])
    assert resumed.model_to_string() == s_straight


def test_unfusable_superepoch_error_names_blocker():
    # train_superepoch called on an unfusable model raises with the
    # specific blocker (fused_reasons), not a generic message
    x, y = _data()
    p = dict(BASE, objective="multiclass", num_class=3,
             metric=["multi_logloss"], fused_chunk=8)
    ds = lgb.Dataset(x[:1600], label=y[:1600] % 3)
    bst = lgb.train(p, ds, num_boost_round=2,
                    keep_training_booster=True)
    with pytest.raises(ValueError, match="num_class"):
        bst._model.train_superepoch(4, 0)
