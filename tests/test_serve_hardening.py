"""Serving hardening tests (ISSUE 5, docs/Serving.md "Hardening").

Deadlines enforced before device work (fail-fast admission + queue
shedding, HTTP 504), the serving circuit breaker (admission-time 503 +
Retry-After while the device side fails, half-open recovery, request
errors never trip it), graceful drain (queued work answered, new work
refused, readiness flips), verified artifacts (manifest SHA-256
checksums, refuse-don't-load on mismatch, engine byte-parity self-check
with host-walk fallback), and the chaos-injection soak harness
(tools/soak_serve.py) run short and deterministic in tier-1.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import (ArtifactVerificationError, BatcherDraining,
                                CircuitOpen, DeadlineExceeded, MicroBatcher,
                                ModelRegistry, PredictorEngine, Server,
                                start_http)
from lightgbm_tpu.utils.resilience import CircuitBreaker

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


def _train(rounds=8, seed=0, n=300, f=5):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    y = x[:, 0] + 0.5 * x[:, 1]
    return lgb.train({"objective": "regression", "verbosity": -1,
                      "num_leaves": 8}, lgb.Dataset(x, label=y),
                     num_boost_round=rounds)


@pytest.fixture(scope="module")
def booster():
    return _train()


# ---------------------------------------------------------------------------
# circuit breaker: state machine (utils/resilience.py)
# ---------------------------------------------------------------------------

class TestCircuitBreakerUnit:
    def _cb(self, **kw):
        clock = {"t": 0.0}
        cb = CircuitBreaker(clock=lambda: clock["t"], **kw)
        return cb, clock

    def test_trips_after_consecutive_failures_only(self):
        cb, _ = self._cb(failure_threshold=3, cooldown_s=1.0)
        for _ in range(2):
            cb.record_failure()
        cb.record_success()              # resets the consecutive count
        for _ in range(2):
            cb.record_failure()
        assert cb.state() == "closed" and cb.allow()
        cb.record_failure()              # 3rd consecutive: trip
        assert cb.state() == "open" and not cb.allow()
        assert cb.opens == 1
        assert 0 < cb.retry_after_s() <= 1.0

    def test_half_open_probe_success_closes_and_resets_cooldown(self):
        cb, clock = self._cb(failure_threshold=1, cooldown_s=1.0,
                             cooldown_max_s=8.0)
        cb.record_failure()
        assert not cb.allow()
        clock["t"] = 1.1
        assert cb.state() == "half_open" and cb.allow()
        cb.record_success()
        assert cb.state() == "closed"
        assert cb.describe()["cooldown_s"] == 1.0

    def test_half_open_failure_doubles_cooldown_capped(self):
        cb, clock = self._cb(failure_threshold=1, cooldown_s=1.0,
                             cooldown_max_s=4.0)
        cb.record_failure()              # open, cooldown 1
        expected = [2.0, 4.0, 4.0]       # doubles, then the cap holds
        for cd in expected:
            clock["t"] += 10.0
            assert cb.allow()            # half-open probe
            cb.record_failure()          # probe fails: re-open
            assert cb.state() == "open"
            assert cb.describe()["cooldown_s"] == cd
        assert cb.opens == 1 + len(expected)

    def test_open_late_failures_do_not_extend_cooldown(self):
        cb, clock = self._cb(failure_threshold=1, cooldown_s=1.0)
        cb.record_failure()
        until = cb.retry_after_s()
        cb.record_failure()              # in-flight straggler
        assert cb.retry_after_s() == until
        assert cb.opens == 1

    def test_half_open_admits_exactly_one_probe(self):
        cb, clock = self._cb(failure_threshold=1, cooldown_s=1.0)
        cb.record_failure()
        clock["t"] = 1.5
        assert cb.allow()                # THE probe
        assert not cb.allow()            # burst behind it: rejected
        assert not cb.allow()
        cb.record_success()              # probe outcome lands
        assert cb.allow() and cb.allow()     # closed: everyone admitted

    def test_abandoned_probe_expires(self):
        cb, clock = self._cb(failure_threshold=1, cooldown_s=1.0)
        cb.record_failure()
        clock["t"] = 1.5
        assert cb.allow()                # probe... whose outcome is lost
        assert not cb.allow()
        clock["t"] = 3.0                 # > probe start + cooldown
        assert cb.allow()                # a new probe may try

    def test_zero_cooldown_floored_still_rejects(self):
        # cooldown 0 must not degenerate into everyone-is-the-probe
        cb, clock = self._cb(failure_threshold=1, cooldown_s=0.0)
        cb.record_failure()
        assert not cb.allow()            # OPEN for the floored cooldown
        clock["t"] = 0.01                # past the floor: HALF_OPEN
        assert cb.allow()                # the single probe
        assert not cb.allow()            # everyone else still rejected

    def test_disabled_breaker_is_inert(self):
        cb, _ = self._cb(failure_threshold=0)
        for _ in range(10):
            cb.record_failure()
        assert cb.allow() and cb.state() == "closed"


# ---------------------------------------------------------------------------
# deadlines: fail-fast admission + queue shedding, never device work
# ---------------------------------------------------------------------------

class TestDeadlines:
    def test_lapsed_deadline_shed_before_dispatch(self):
        from lightgbm_tpu.obs import MetricsRegistry
        m = MetricsRegistry()
        hold = threading.Event()
        seen = []

        def fn(rows):
            seen.append(len(rows))
            hold.wait(10)
            return rows[:, 0]

        gate = MicroBatcher(fn, max_batch=4, max_wait_ms=0.0, metrics=m)
        try:
            f1 = gate.submit(np.zeros((1, 2)))
            time.sleep(0.05)             # worker wedged on batch 1
            f2 = gate.submit(np.zeros((2, 2)), deadline_ms=60.0)
            time.sleep(0.15)             # deadline lapses while queued
            hold.set()
            with pytest.raises(DeadlineExceeded) as ei:
                f2.result(5)
            assert ei.value.where == "queue"
            assert ei.value.waited_ms >= 60.0
            f1.result(5)
        finally:
            hold.set()
            gate.close()
        # the shed request NEVER reached the predict function
        assert seen == [1]
        assert m.snapshot()["serve.deadline_shed"]["value"] == 1

    def test_hopeless_deadline_rejected_at_admission(self):
        hold = threading.Event()
        gate = MicroBatcher(lambda r: (hold.wait(10), r[:, 0])[1],
                            max_batch=2, max_wait_ms=100.0)
        try:
            f1 = gate.submit(np.zeros((2, 1)))
            time.sleep(0.05)
            f2 = gate.submit(np.zeros((2, 1)))   # one pending batch:
            # estimated wait is >= the 100 ms window
            with pytest.raises(DeadlineExceeded) as ei:
                gate.submit(np.zeros((1, 1)), deadline_ms=50.0)
            assert ei.value.where == "admission"
            # a deadline the estimate can meet is admitted
            f3 = gate.submit(np.zeros((1, 1)), deadline_ms=5000.0)
            hold.set()
            for f in (f1, f2, f3):
                f.result(5)
        finally:
            hold.set()
            gate.close()

    def test_admission_floor_uses_measured_service_time(self):
        # full batches dispatch on FILL, so the coalescing window is
        # not a wait floor for them: once a batch has completed, the
        # estimate is measured service time — a queue that drains in
        # ~1ms must not 504 a deadline the window heuristic exceeds
        hold = threading.Event()
        seen = []

        def fn(rows):
            seen.append(len(rows))
            if len(seen) == 2:
                hold.wait(10)
            return rows[:, 0]

        b = MicroBatcher(fn, max_batch=2, max_wait_ms=100.0)
        try:
            b.submit(np.zeros((2, 1))).result(5)   # trains the EWMA
            f1 = b.submit(np.zeros((2, 1)))        # dispatches; blocks
            time.sleep(0.05)
            f2 = b.submit(np.zeros((2, 1)))        # one batch pending
            # window heuristic: 1 batch x 100ms window > 90ms deadline
            # -> the pre-fix code rejected at admission; the measured
            # sub-ms service floor admits it
            f3 = b.submit(np.zeros((1, 1)), deadline_ms=90.0)
            hold.set()
            f3.result(5)
            f1.result(5)
            f2.result(5)
        finally:
            hold.set()
            b.close()

    def test_server_default_deadline_from_config(self, booster):
        srv = Server({"serve_deadline_ms": 60.0, "serve_max_wait_ms": 0.0},
                     booster=booster)
        hold = threading.Event()
        real = srv.batcher.predict_fn
        srv.batcher.predict_fn = lambda rows: (hold.wait(10),
                                               real(rows))[1]
        try:
            f1 = srv.submit(np.zeros((1, 5)))
            time.sleep(0.15)
            f2 = srv.submit(np.zeros((1, 5)))   # inherits the default
            time.sleep(0.15)
            hold.set()
            f1.result(5)
            with pytest.raises(DeadlineExceeded):
                f2.result(5)
            # an explicit per-request deadline overrides the default
            assert srv.predict(np.zeros((1, 5)), timeout=5,
                               deadline_ms=30000.0) is not None
        finally:
            hold.set()
            srv.close()

    def test_http_504_on_deadline(self, booster):
        srv = Server({"serve_max_wait_ms": 0.0}, booster=booster)
        hold = threading.Event()
        real = srv.batcher.predict_fn
        srv.batcher.predict_fn = lambda rows: (hold.wait(10),
                                               real(rows))[1]
        fe = start_http(srv, port=0)
        try:
            f1 = srv.submit(np.zeros((1, 5)))
            time.sleep(0.1)
            req = urllib.request.Request(
                f"http://127.0.0.1:{fe.port}/predict",
                data=json.dumps({"rows": [[0.0] * 5],
                                 "deadline_ms": 80.0}).encode(),
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()

            def release():
                time.sleep(0.3)
                hold.set()

            threading.Thread(target=release, daemon=True).start()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 504
            body = json.loads(ei.value.read())
            assert body["deadline_ms"] == pytest.approx(80.0)
            assert time.perf_counter() - t0 < 8.0
            f1.result(5)
        finally:
            hold.set()
            fe.close()
            srv.close()


# ---------------------------------------------------------------------------
# circuit breaker: serving semantics
# ---------------------------------------------------------------------------

class TestServingBreaker:
    def _failing_server(self, booster, **params):
        srv = Server({"serve_retries": 0, "serve_breaker_failures": 2,
                      "serve_breaker_cooldown_ms": 150.0,
                      "serve_max_wait_ms": 0.0, **params},
                     booster=booster)
        return srv

    def test_opens_rejects_and_recovers(self, booster):
        srv = self._failing_server(booster)
        real = srv.batcher.predict_fn

        def boom(rows):
            raise RuntimeError("device UNAVAILABLE (simulated wedge)")

        srv.batcher.predict_fn = boom
        x = np.zeros((1, 5))
        try:
            for _ in range(2):
                with pytest.raises(RuntimeError):
                    srv.predict(x, timeout=5)
            with pytest.raises(CircuitOpen) as ei:
                srv.submit(x)
            assert ei.value.retry_after_ms > 0
            h = srv.health()
            # degraded stays READY: the half-open probe is an ordinary
            # request, so an LB must keep routing some traffic here
            assert h["status"] == "degraded" and h["ready"] is True
            assert h["breaker"]["state"] == "open"
            snap = srv.metrics_snapshot()
            assert snap["serve.breaker_opens"]["value"] == 1
            assert snap["serve.breaker_rejected"]["value"] >= 1
            assert snap["serve.breaker_state"]["value"] == 2
            # recovery: fix the device, wait out the cooldown, and the
            # half-open probe closes the circuit
            srv.batcher.predict_fn = real
            deadline = time.time() + 10
            while True:
                try:
                    srv.predict(x, timeout=5)
                    break
                except CircuitOpen:
                    assert time.time() < deadline, "breaker never half-opened"
                    time.sleep(0.03)
            assert srv.breaker.describe()["state"] == "closed"
            assert srv.health()["status"] == "ok"
        finally:
            srv.close()

    def test_request_scoped_errors_never_trip(self, booster):
        srv = self._failing_server(booster)
        x = np.zeros((1, 5))
        try:
            # wrong feature count -> LightGBMError (ValueError family):
            # each request fails alone, the breaker must not move
            for _ in range(4):
                with pytest.raises(Exception):
                    srv.predict(np.zeros((1, 2)), timeout=5)
            assert srv.breaker.describe()["state"] == "closed"
            assert srv.predict(x, timeout=5) is not None
        finally:
            srv.close()

    def test_http_503_with_retry_after(self, booster):
        srv = self._failing_server(booster)
        srv.batcher.predict_fn = \
            lambda rows: (_ for _ in ()).throw(RuntimeError("UNAVAILABLE"))
        fe = start_http(srv, port=0)
        base = f"http://127.0.0.1:{fe.port}"
        try:
            for _ in range(2):
                with pytest.raises(urllib.error.HTTPError):
                    self._post(base, {"rows": [[0.0] * 5]})
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(base, {"rows": [[0.0] * 5]})
            assert ei.value.code == 503
            assert int(ei.value.headers["Retry-After"]) >= 1
            assert json.loads(ei.value.read())["retry_after_ms"] > 0
            # healthz stays 200 while merely degraded (alive, LBs may
            # deprioritize via the body) — not 503
            h = json.loads(urllib.request.urlopen(base + "/healthz").read())
            assert h["status"] == "degraded"
        finally:
            fe.close()
            srv.close()

    @staticmethod
    def _post(base, payload):
        req = urllib.request.Request(
            base + "/predict", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req, timeout=10).read())

    def test_breaker_disabled_by_config(self, booster):
        srv = Server({"serve_breaker_failures": 0}, booster=booster)
        try:
            assert srv.breaker is None
            assert "breaker" not in srv.health()
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------

class TestDrain:
    def test_drain_answers_queued_then_refuses_new(self, booster):
        srv = Server({"serve_max_batch": 2, "serve_max_wait_ms": 0.0},
                     booster=booster)
        hold = threading.Event()
        real = srv.batcher.predict_fn
        srv.batcher.predict_fn = lambda rows: (hold.wait(10),
                                               real(rows))[1]
        x = np.zeros((2, 5))
        f1 = srv.submit(x)
        time.sleep(0.05)
        f2 = srv.submit(x)               # queued behind the wedge
        result = {}

        def drain():
            result.update(srv.drain(10.0))

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        time.sleep(0.05)
        # draining: new work refused, health flips, old work completes
        with pytest.raises(BatcherDraining):
            srv.submit(x)
        h = srv.health()
        assert h["status"] == "draining" and h["ready"] is False
        hold.set()
        t.join(10)
        assert result["drained"] is True and result["leftover_rows"] == 0
        f1.result(5), f2.result(5)
        assert srv.batcher.depth_rows == 0
        srv.close()

    def test_drain_prompt_when_last_round_all_shed(self):
        """A drain whose final collect round sheds EVERYTHING (all
        deadlines lapsed, nothing dispatched) must still wake
        wait_idle immediately, not sleep out the full budget.

        The deadline must lapse while the worker is BUSY with an
        earlier batch — the coalescing window itself closes before a
        queued deadline, so an idle batcher dispatches in time instead
        of shedding."""
        hold = threading.Event()

        def fn(rows):
            hold.wait(5.0)
            return rows[:, 0]

        gate = MicroBatcher(fn, max_batch=8, max_wait_ms=10.0)
        f1 = gate.submit(np.zeros((2, 1)))      # occupies the worker
        time.sleep(0.05)                        # worker now inside fn
        f2 = gate.submit(np.zeros((2, 1)), deadline_ms=60.0)
        time.sleep(0.1)                         # f2 lapses while queued
        gate.begin_drain()
        hold.set()
        t0 = time.perf_counter()
        assert gate.wait_idle(5.0) is True
        assert time.perf_counter() - t0 < 2.0   # shed wakes it, not 5s
        np.testing.assert_array_equal(f1.result(1), np.zeros(2))
        with pytest.raises(DeadlineExceeded):
            f2.result(1)
        gate.close()

    def test_http_drain_and_healthz_503(self, booster):
        srv = Server({}, booster=booster)
        fe = start_http(srv, port=0)
        base = f"http://127.0.0.1:{fe.port}"
        try:
            h = json.loads(urllib.request.urlopen(base + "/healthz").read())
            assert h["ready"] is True
            req = urllib.request.Request(base + "/drain", data=b"{}")
            resp = json.loads(urllib.request.urlopen(req, timeout=10).read())
            assert resp["drained"] is True
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/healthz")
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["status"] == "draining"
            # predict during drain: 503, not a hang
            with pytest.raises(urllib.error.HTTPError) as ei:
                TestServingBreaker._post(base, {"rows": [[0.0] * 5]})
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["draining"] is True
        finally:
            fe.close()
            srv.close()

    def test_cli_sigterm_drains_gracefully(self, tmp_path):
        model = str(tmp_path / "m.txt")
        _train().save_model(model)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "lightgbm_tpu", "serve",
             f"input_model={model}", "serve_port=0", "serve_drain_s=5"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd=os.path.join(os.path.dirname(__file__), ".."))
        try:
            t0 = time.time()
            line = b""
            while time.time() - t0 < 90:
                line = proc.stdout.readline()
                if b"serving" in line:
                    break
            assert b"serving" in line, "server never came up"
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=60)
            assert proc.returncode == 0, out.decode()
            assert b"draining" in out and b"drain complete" in out, \
                out.decode()
        finally:
            if proc.poll() is None:
                proc.kill()


# ---------------------------------------------------------------------------
# verified artifacts
# ---------------------------------------------------------------------------

class TestVerifiedArtifacts:
    def _snapshots(self, tmp_path, rounds=6):
        rs = np.random.RandomState(3)
        x = rs.randn(300, 5)
        y = x[:, 0]
        out = str(tmp_path / "model.txt")
        lgb.train({"objective": "regression", "verbosity": -1,
                   "output_model": out, "snapshot_freq": 2,
                   "snapshot_keep": 0}, lgb.Dataset(x, label=y),
                  num_boost_round=rounds)
        return out

    def test_manifest_records_matching_checksums(self, tmp_path):
        from lightgbm_tpu.snapshot import file_sha256
        out = self._snapshots(tmp_path)
        path = out + ".snapshot_iter_6"
        with open(path + ".manifest.json") as f:
            man = json.load(f)
        assert man["model_sha256"] == file_sha256(path)
        assert man["state_sha256"] == file_sha256(path + ".state.npz")

    def test_corrupted_snapshot_skipped_for_older(self, tmp_path):
        from lightgbm_tpu.snapshot import (find_latest_complete_snapshot,
                                           verify_snapshot_artifacts)
        out = self._snapshots(tmp_path)
        newest = out + ".snapshot_iter_6"
        with open(newest, "a") as f:
            f.write("\ncorruption")      # bit rot / torn write
        with open(newest + ".manifest.json") as f:
            assert "checksum mismatch" in \
                verify_snapshot_artifacts(newest, json.load(f))
        it, path = find_latest_complete_snapshot(out)
        assert it == 4                   # fell back past the corruption
        reg = ModelRegistry()
        v = reg.load_snapshot(out)
        assert "iter 4" in reg.get(v).source

    def test_snapshot_load_honors_caller_pin(self, tmp_path):
        # a caller pin on the SNAPSHOT form must be enforced, not
        # silently replaced by the manifest's self-checksum
        from lightgbm_tpu.snapshot import (file_sha256,
                                           find_latest_complete_snapshot)
        out = self._snapshots(tmp_path)
        reg = ModelRegistry()
        with pytest.raises(ArtifactVerificationError):
            reg.load_snapshot(out, expected_sha256="a" * 64)
        assert reg.versions() == []
        _, path = find_latest_complete_snapshot(out)
        v = reg.load_snapshot(out,
                              expected_sha256=file_sha256(path))
        assert reg.get(v).version == v

    def test_corrupted_state_skipped_for_training_resume(self, tmp_path):
        from lightgbm_tpu.snapshot import verify_snapshot_artifacts
        out = self._snapshots(tmp_path)
        newest = out + ".snapshot_iter_6"
        with open(newest + ".state.npz", "ab") as f:
            f.write(b"xx")
        with open(newest + ".manifest.json") as f:
            err = verify_snapshot_artifacts(newest, json.load(f))
        assert err and "state.npz" in err

    def test_registry_refuses_checksum_mismatch(self, tmp_path, booster):
        path = str(tmp_path / "m.txt")
        booster.save_model(path)
        reg = ModelRegistry()
        with pytest.raises(ArtifactVerificationError):
            reg.load(model_file=path, expected_sha256="0" * 64)
        assert reg.versions() == []      # nothing half-registered
        from lightgbm_tpu.snapshot import file_sha256, sha256_hex
        v = reg.load(model_file=path,
                     expected_sha256=file_sha256(path))
        assert reg.get(v).version == v
        # model_str pins verify against the string's bytes
        s = booster.model_to_string()
        with pytest.raises(ArtifactVerificationError):
            reg.load(model_str=s, expected_sha256="1" * 64)
        reg.load(model_str=s, expected_sha256=sha256_hex(s))
        # a live booster has no byte artifact: the pin is refused, not
        # silently ignored
        with pytest.raises(ValueError, match="expected_sha256"):
            reg.load(booster=booster, expected_sha256=sha256_hex(s))

    def test_http_reload_409_on_bad_sha(self, tmp_path, booster):
        path = str(tmp_path / "m.txt")
        booster.save_model(path)
        srv = Server({}, booster=booster)
        fe = start_http(srv, port=0)
        base = f"http://127.0.0.1:{fe.port}"
        try:
            req = urllib.request.Request(
                base + "/reload",
                data=json.dumps({"model_file": path,
                                 "sha256": "f" * 64}).encode())
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 409
            # the current version keeps serving
            assert srv.health()["model"]["version"] == "v1"
            assert srv.metrics_snapshot()["serve.reload_failures"][
                "value"] == 1
            ok = TestServingBreaker._post(
                base, {"rows": np.zeros((1, 5)).tolist()})
            assert ok["model_version"] == "v1"
        finally:
            fe.close()
            srv.close()

    def test_self_check_covers_device_binning_path(self, booster):
        from lightgbm_tpu.serve.engine import EngineUnsupported
        # the path serve_device_binning actually serves must be part of
        # the verification gate, on rows where f32 == f64 binning
        eng = PredictorEngine.from_booster(booster)
        assert eng.self_check(device_binning=True) is True
        assert eng._f32_consensus_mask(
            np.zeros((4, booster.num_feature()))).all()
        # categoricals device-bin integer-exactly since ISSUE 10 (the
        # fused serve path needs them): the check covers that path too
        rs = np.random.RandomState(11)
        x = np.column_stack([rs.randint(0, 4, 200).astype(np.float64),
                             rs.randn(200)])
        cat = lgb.train({"objective": "regression", "verbosity": -1,
                         "num_leaves": 6, "min_data_per_group": 1},
                        lgb.Dataset(x, label=x[:, 1] + (x[:, 0] == 2),
                                    categorical_feature=[0]),
                        num_boost_round=4)
        ceng = PredictorEngine.from_booster(cat)
        assert ceng.self_check() is True
        assert ceng.self_check(device_binning=True) is True
        # ...but categories beyond f32's exact integer range (>= 2^24)
        # would misroute in the f32 compare: the check raises (registry
        # treats an erroring probe as failed -> host-walk fallback)
        big = np.column_stack([
            np.repeat([1.0, float(1 << 24) + 2.0], 100), rs.randn(200)])
        bigm = lgb.train({"objective": "regression", "verbosity": -1,
                          "num_leaves": 4, "min_data_per_group": 1,
                          "min_data_in_leaf": 5},
                         lgb.Dataset(big, label=big[:, 1]
                                     + (big[:, 0] > 2),
                                     categorical_feature=[0]),
                         num_boost_round=4)
        beng = PredictorEngine.from_booster(bigm)
        if beng._device_bin_err is None:
            pytest.skip("model grew no >=2^24 categorical split")
        assert not beng.fused_ok
        with pytest.raises(EngineUnsupported):
            beng.self_check(device_binning=True)

    def test_empty_sha256_pin_refused(self, tmp_path, booster):
        # an empty pin is an unset deploy-script variable, never a
        # request to skip verification
        path = str(tmp_path / "m.txt")
        booster.save_model(path)
        reg = ModelRegistry()
        with pytest.raises(ValueError, match="non-empty"):
            reg.load(model_file=path, expected_sha256="")
        assert reg.versions() == []

    def test_engine_self_check_catches_corruption(self, booster):
        eng = PredictorEngine.from_booster(booster)
        assert eng.self_check() is True
        # corrupt the DEVICE-side SoA the traversal actually reads:
        # shifting every threshold bin flips the probe's exact-tie rows
        eng._dev["threshold_bin"] = eng._dev["threshold_bin"] + 1
        assert eng.self_check() is False

    def test_registry_falls_back_when_self_check_fails(self, booster,
                                                       monkeypatch):
        monkeypatch.setattr(PredictorEngine, "self_check",
                            lambda self, **kw: False)
        reg = ModelRegistry()
        v = reg.load(booster=booster)
        served = reg.get(v)
        assert served.engine is None     # discarded, host walk serves
        x = np.random.RandomState(5).randn(7, 5)
        assert np.array_equal(served.booster.predict(x),
                              _train().predict(x))

    def test_failed_reload_keeps_current_serving(self, booster):
        from lightgbm_tpu.utils import faultinject
        srv = Server({}, booster=booster)
        x = np.zeros((3, 5))
        try:
            ref = srv.predict(x, timeout=10)
            faultinject.configure("serve_reload:1")
            with pytest.raises(Exception, match="injected"):
                srv.reload(booster=_train(rounds=3, seed=9))
            faultinject.clear()
            assert srv.health()["model"]["version"] == "v1"
            assert np.array_equal(srv.predict(x, timeout=10), ref)
        finally:
            faultinject.clear()
            srv.close()


# ---------------------------------------------------------------------------
# chaos soak (tools/soak_serve.py) — short tier-1 run
# ---------------------------------------------------------------------------

class TestChaosSoak:
    def test_short_soak_no_violations(self):
        import soak_serve
        report = soak_serve.run_soak(duration_s=1.2, clients=3,
                                     chaos=True, seed=1)
        assert report["violations"] == [], report
        assert report["counts"]["ok"] > 0
        assert report["recovered"] is True
        assert report["drain"]["drained"] is True

    def test_soak_without_chaos_is_clean_and_error_free(self):
        import soak_serve
        report = soak_serve.run_soak(duration_s=0.8, clients=2,
                                     chaos=False, seed=2)
        assert report["violations"] == [], report
        assert report["counts"].get("error", 0) == 0
        assert report["counts"].get("reload_failed", 0) == 0


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

class TestHardeningConfig:
    def test_defaults_and_validation(self):
        from lightgbm_tpu.config import Config
        cfg = Config({})
        assert cfg.serve_deadline_ms == 0.0
        assert cfg.serve_breaker_failures == 5
        assert cfg.serve_breaker_cooldown_ms == 1000.0
        assert cfg.serve_drain_s == 5.0
        assert cfg.serve_verify_artifacts is True
        assert Config({"serve_default_deadline_ms": 250.0}
                      ).serve_deadline_ms == 250.0
        for bad in ({"serve_deadline_ms": -1},
                    {"serve_breaker_failures": -1},
                    {"serve_breaker_cooldown_ms": -1},
                    # 0 would make every caller the half-open probe —
                    # an open circuit that never rejects anything
                    {"serve_breaker_cooldown_ms": 0},
                    {"serve_drain_s": -0.5}):
            with pytest.raises(ValueError):
                Config(bad)

    def test_new_fault_sites_known(self):
        from lightgbm_tpu.utils import faultinject
        assert "serve_batch" in faultinject.KNOWN_SITES
        assert "serve_reload" in faultinject.KNOWN_SITES
        faultinject.configure("serve_batch:2")
        try:
            faultinject.check("serve_batch")       # hit 1: no fire
            with pytest.raises(faultinject.InjectedFault):
                faultinject.check("serve_batch")   # hit 2: fires
        finally:
            faultinject.clear()
