"""Distributed dataset construction: sketch-merged bin-mapper fitting.

Analog of the reference's distributed binning
(/root/reference/src/io/dataset_loader.cpp:1104-1186), upgraded to the
shape arXiv:1804.06755 ("Exact Distributed Training ... Billions of
Examples") prescribes: every process folds its OWN ROWS into mergeable
per-feature quantile sketches (``binning.QuantileSketch``), the
serialized sketches are allgathered, and every process deterministically
merges them in rank order and fits FindBin over the merged summaries —
so the global bin bounds see EVERY row of every shard, no host ever
materializes another shard's samples, and the wire carries
capacity-bounded sketches instead of raw sample matrices
(arXiv:1611.01276's ship-summaries-not-samples argument).

The legacy feature-sharded mode (``method="shard"``: features split
across ranks, each rank FindBins its slice on its LOCAL rows only, then
mappers are allgathered) is retained for comparison; its bounds only
reflect the fitting rank's shard.

Wire format: every allgathered payload is framed —
``LGTF | version u16 | length u64 | sha256[32] | body`` — and unframing
VERIFIES before unpickling (:func:`frame_payload` /
:func:`unframe_payload`).  A corrupt or truncated peer payload raises
:class:`PayloadIntegrityError`, whose message carries the resilience
classifier's retryable patterns so ``elastic.failure_kind`` classifies
it instead of the process dying inside arbitrary unpickle behavior.

The collective rides jax.distributed (multihost_utils.process_allgather)
instead of the reference's hand-rolled socket Allgather (network.cpp:156);
an injectable ``allgather`` hook keeps it testable in-process.
"""

from __future__ import annotations

import hashlib
import pickle
from typing import Callable, List, Optional

import numpy as np

from ..binning import (BinMapper, BinType, QuantileSketch,
                       fit_mappers_from_sketches, sketch_features)
from ..config import Config

# framed-payload wire format (docs/Distributed-Learning.md)
_FRAME_MAGIC = b"LGTF"
_FRAME_VERSION = 1
_HEADER_LEN = len(_FRAME_MAGIC) + 2 + 8 + 32

# running count of payload bytes this process has allgathered for
# binning — bench.py's ``binning_wire_bytes`` extra reads it
_WIRE_BYTES = {"sent": 0}


def wire_bytes_sent() -> int:
    """Framed binning payload bytes this process has sent (monotonic)."""
    return _WIRE_BYTES["sent"]


def reset_wire_bytes() -> None:
    _WIRE_BYTES["sent"] = 0


class PayloadIntegrityError(RuntimeError):
    """An allgathered peer payload failed framing verification.  The
    message deliberately matches the resilience classifier's retryable
    patterns (UNAVAILABLE) — a torn payload is a transport failure the
    elastic ladder may retry/shrink around, not a programming error."""

    def __init__(self, detail: str):
        super().__init__(
            f"UNAVAILABLE: corrupt allgathered payload ({detail})")


def frame_payload(body: bytes) -> bytes:
    """``LGTF | version | length | sha256 | body`` — self-verifying."""
    return (_FRAME_MAGIC
            + _FRAME_VERSION.to_bytes(2, "little")
            + len(body).to_bytes(8, "little")
            + hashlib.sha256(body).digest()
            + body)


def unframe_payload(blob: bytes) -> bytes:
    """Verify and strip a :func:`frame_payload` frame.  Raises
    :class:`PayloadIntegrityError` on magic/version/length/sha mismatch
    — BEFORE any byte of the body reaches ``pickle.loads``."""
    if len(blob) < _HEADER_LEN:
        raise PayloadIntegrityError(
            f"truncated header: {len(blob)} bytes < {_HEADER_LEN}")
    if blob[:4] != _FRAME_MAGIC:
        raise PayloadIntegrityError(f"bad magic {blob[:4]!r}")
    version = int.from_bytes(blob[4:6], "little")
    if version != _FRAME_VERSION:
        raise PayloadIntegrityError(
            f"unsupported frame version {version}")
    n = int.from_bytes(blob[6:14], "little")
    body = blob[_HEADER_LEN:_HEADER_LEN + n]
    if len(body) != n:
        raise PayloadIntegrityError(
            f"truncated body: header says {n} bytes, got {len(body)}")
    if hashlib.sha256(body).digest() != blob[14:46]:
        raise PayloadIntegrityError("sha256 mismatch")
    return body


def shard_features(num_features: int, num_machines: int):
    """Contiguous balanced feature slices (dataset_loader.cpp:1106-1117)."""
    step = max((num_features + num_machines - 1) // num_machines, 1)
    start, length = [0] * num_machines, [0] * num_machines
    for i in range(num_machines - 1):
        length[i] = min(step, num_features - start[i])
        start[i + 1] = start[i] + length[i]
    length[num_machines - 1] = num_features - start[num_machines - 1]
    return start, length


def _jax_allgather_bytes(payload: bytes) -> List[bytes]:
    """Variable-length byte allgather over jax.distributed processes."""
    import jax
    from jax.experimental import multihost_utils

    arr = np.frombuffer(payload, np.uint8)
    n = np.int64(len(arr))
    sizes = np.asarray(multihost_utils.process_allgather(n))
    maxlen = int(sizes.max())
    padded = np.zeros(maxlen, np.uint8)
    padded[:len(arr)] = arr
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(jax.process_count(), maxlen)
    return [gathered[i, :int(sizes[i])].tobytes()
            for i in range(jax.process_count())]


def _exchange(obj, allgather: Callable[[bytes], List[bytes]]) -> List:
    """pickle -> frame -> allgather -> verify each peer -> unpickle."""
    payload = frame_payload(pickle.dumps(obj, protocol=4))
    _WIRE_BYTES["sent"] += len(payload)
    out = []
    for rank, blob in enumerate(allgather(payload)):
        try:
            body = unframe_payload(blob)
        except PayloadIntegrityError as e:
            raise PayloadIntegrityError(
                f"rank {rank}: {e}") from None
        out.append(pickle.loads(body))
    return out


def distributed_bin_mappers(
        local_sample: np.ndarray, config: Config,
        cat_idx: Optional[set] = None,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        allgather: Optional[Callable[[bytes], List[bytes]]] = None,
        method: str = "sketch",
) -> List[BinMapper]:
    """Fit globally-consistent bin mappers from per-process row shards.

    local_sample: this process's sampled raw rows [n_local_sample, F]
    Returns the full list of F bin mappers, identical on every process.

    ``method="sketch"`` (default): every process sketches ALL features
    over its rows; sketches are allgathered and merged in rank order —
    deterministic, sees every shard's rows, wire size bounded by
    ``ingest_sketch_size``.  ``method="shard"``: the legacy
    feature-sharded FindBin (each feature's bounds reflect one rank's
    rows only).
    """
    cat_idx = cat_idx or set()
    if process_index is None or process_count is None:
        import jax
        process_index = jax.process_index()
        process_count = jax.process_count()
    if allgather is None:
        allgather = _jax_allgather_bytes
    if method == "sketch":
        return _sketch_bin_mappers(local_sample, config, cat_idx,
                                   allgather)
    if method != "shard":
        raise ValueError(f"unknown distributed binning method "
                         f"{method!r} (want sketch or shard)")

    f_total = local_sample.shape[1]
    start, length = shard_features(f_total, process_count)
    lo = start[process_index]
    hi = lo + length[process_index]
    own: List[dict] = []
    n = len(local_sample)
    mbf = config.max_bin_by_feature
    for f in range(lo, hi):
        m = BinMapper()
        mb = int(mbf[f]) if mbf else config.max_bin
        bt = BinType.CATEGORICAL if f in cat_idx else BinType.NUMERICAL
        m.find_bin(local_sample[:, f], n, mb, config.min_data_in_bin,
                   min_split_data=config.min_data_in_leaf,
                   pre_filter=config.feature_pre_filter, bin_type=bt,
                   use_missing=config.use_missing,
                   zero_as_missing=config.zero_as_missing)
        own.append(m.to_state())
    shards = _exchange(own, allgather)
    mappers: List[BinMapper] = []
    for states in shards:
        for st in states:
            mappers.append(BinMapper.from_state(st))
    if len(mappers) != f_total:
        raise RuntimeError(
            f"distributed binning produced {len(mappers)} mappers for "
            f"{f_total} features — rank slices out of sync")
    return mappers


def _sketch_bin_mappers(local_sample: np.ndarray, config: Config,
                        cat_idx: set,
                        allgather: Callable[[bytes], List[bytes]]
                        ) -> List[BinMapper]:
    f_total = local_sample.shape[1]
    cap = int(getattr(config, "ingest_sketch_size", 2048))
    own = [QuantileSketch(cap, categorical=(f in cat_idx))
           for f in range(f_total)]
    sketch_features(np.asarray(local_sample, np.float64), own)
    shards = _exchange([s.to_state() for s in own], allgather)
    merged: Optional[List[QuantileSketch]] = None
    for rank, states in enumerate(shards):
        if len(states) != f_total:
            raise PayloadIntegrityError(
                f"rank {rank} sent {len(states)} sketches for "
                f"{f_total} features")
        sks = [QuantileSketch.from_state(st) for st in states]
        if merged is None:
            merged = sks
        else:
            # rank-order merge: identical on every process, so the
            # fitted bounds are byte-identical fleet-wide
            for m, s in zip(merged, sks):
                m.merge(s)
    assert merged is not None
    return fit_mappers_from_sketches(merged, config, cat_idx)
