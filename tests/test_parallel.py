"""Distributed learner tests on a virtual 8-device CPU mesh.

The reference tests distributed training by simulating machines with
localhost sockets (tests/distributed/_test_distributed.py); here the mesh
IS the simulation: data-parallel and feature-parallel growers must produce
exactly the same tree as the serial grower.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.grower import make_grower
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.parallel import (make_dp_grower, make_fp_grower, make_mesh,
                                   make_voting_grower, owner_hist_reduce,
                                   owner_shard_plan, shard_rows)


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh((8,), ("data",))


@pytest.fixture(scope="module")
def mesh_feat():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    return make_mesh((4,), ("feature",))


def _data(n=4096, f=8, b=16, seed=0):
    rng = np.random.RandomState(seed)
    binned = rng.randint(0, b, size=(n, f)).astype(np.uint8)
    y = (binned[:, 2] >= b // 2).astype(np.float32) \
        + 0.3 * rng.randn(n).astype(np.float32)
    g = (0.5 - y).astype(np.float32)
    vals = np.stack([g, np.ones(n, np.float32), np.ones(n, np.float32)], axis=1)
    return binned, vals


def _tree_fields(tree, skip=("leaf_of_row",)):
    return {k: np.asarray(v) for k, v in tree._asdict().items()
            if k not in skip}


class TestDataParallel:
    def test_matches_serial(self, mesh8):
        binned, vals = _data()
        F, B, L = binned.shape[1], 16, 8
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(F, B, jnp.int32)
        na = jnp.full(F, -1, jnp.int32)
        fm = jnp.ones(F, bool)

        serial = make_grower(num_leaves=L, num_bins=B, params=p)
        t_ser = serial(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na)

        dp = make_dp_grower(mesh8, num_leaves=L, num_bins=B, params=p)
        t_dp = dp(shard_rows(mesh8, binned), shard_rows(mesh8, vals),
                  fm, nb, na)

        ser_f = _tree_fields(t_ser)
        dp_f = _tree_fields(t_dp)
        assert int(t_ser.num_leaves) == int(t_dp.num_leaves) > 2
        for k in ("split_feature", "threshold_bin", "left_child", "right_child"):
            np.testing.assert_array_equal(ser_f[k], dp_f[k], err_msg=k)
        np.testing.assert_allclose(ser_f["leaf_value"], dp_f["leaf_value"],
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(ser_f["leaf_count"], dp_f["leaf_count"])
        # row partition agrees (dp leaf_of_row is row-sharded, same order)
        np.testing.assert_array_equal(np.asarray(t_ser.leaf_of_row),
                                      np.asarray(t_dp.leaf_of_row))

    def test_uneven_work_masking(self, mesh8):
        # zero-weight rows on some shards (bagging) keep results consistent
        binned, vals = _data(seed=3)
        vals[::3, :] = 0.0  # "out of bag"
        F, B, L = binned.shape[1], 16, 6
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(F, B, jnp.int32)
        na = jnp.full(F, -1, jnp.int32)
        fm = jnp.ones(F, bool)
        serial = make_grower(num_leaves=L, num_bins=B, params=p)
        t_ser = serial(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na)
        dp = make_dp_grower(mesh8, num_leaves=L, num_bins=B, params=p)
        t_dp = dp(shard_rows(mesh8, binned), shard_rows(mesh8, vals), fm, nb, na)
        np.testing.assert_array_equal(np.asarray(t_ser.split_feature),
                                      np.asarray(t_dp.split_feature))
        np.testing.assert_allclose(np.asarray(t_ser.leaf_value),
                                   np.asarray(t_dp.leaf_value),
                                   rtol=1e-4, atol=1e-5)


class TestOwnerShard:
    """The reduce-scatter owner-shard dp learner (ISSUE 1 tentpole):
    per-shard histogram state is the owned chunk of the GLOBAL
    histograms, the split scan runs on that slice, and only the best
    SplitResult is allgathered — the reference's ReduceScatter +
    SyncUpGlobalBestSplit communication shape
    (data_parallel_tree_learner.cpp:174-186)."""

    def test_plan_roundtrip_efb_group_padding(self):
        # uneven EFB groups (G=5 over 4 shards -> padded to 8 group rows):
        # the feature-chunk -> global-feature-id map must cover every
        # feature exactly once, pads must be -1, and each owned feature's
        # group must lie inside its shard's group chunk
        group_of = np.array([0, 0, 0, 1, 2, 2, 3, 4, 4, 4, 4])
        plan = owner_shard_plan(group_of, 4)
        assert plan.chunk == 2          # ceil(5 groups / 4 shards)
        assert plan.n_shards == 4
        sf = plan.shard_feat
        feats = sf[sf >= 0]
        assert sorted(feats.tolist()) == list(range(len(group_of)))
        assert plan.fmax == max((sf[s] >= 0).sum() for s in range(4))
        for s in range(4):
            owned = sf[s][sf[s] >= 0]
            assert ((group_of[owned] >= s * plan.chunk)
                    & (group_of[owned] < (s + 1) * plan.chunk)).all()
            # slots after the owned prefix are all padding
            k = len(owned)
            assert (sf[s][k:] == -1).all()

    def test_plan_identity_when_unbundled(self):
        # without EFB the group axis IS the feature axis: contiguous
        # equal chunks, scan width == chunk
        plan = owner_shard_plan(np.arange(10), 8)
        assert plan.chunk == 2 and plan.fmax == 2
        np.testing.assert_array_equal(plan.shard_feat[0], [0, 1])
        np.testing.assert_array_equal(plan.shard_feat[4], [8, 9])
        assert (plan.shard_feat[5:] == -1).all()

    def test_reduce_scatter_owned_hist_shape(self, mesh8):
        # the per-shard histogram state after the reduce is the owned
        # [ceil(F/8), B, 3] chunk of the GLOBAL histogram — the shape
        # assertion behind the [L, F/n_shards, B, 3] grower carry
        from lightgbm_tpu.utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P
        F, B = 11, 16
        plan = owner_shard_plan(np.arange(F), 8)
        assert plan.chunk == 2
        red = owner_hist_reduce("data", 8, plan.chunk)
        rng = np.random.RandomState(0)
        local = rng.rand(8, F, B, 3).astype(np.float32)  # per-shard hists

        fn = jax.jit(shard_map(
            lambda h: red(h[0]), mesh=mesh8,
            in_specs=(P("data", None, None, None),),
            out_specs=P("data", None, None), check_vma=False))
        out = np.asarray(fn(local))
        # global stacked output = 8 shards x chunk rows of GLOBAL sums
        assert out.shape == (8 * plan.chunk, B, 3)
        ref = np.zeros((8 * plan.chunk, B, 3), np.float32)
        ref[:F] = local.sum(axis=0)
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)

    @pytest.mark.parametrize("split_batch", [1, 8])
    @pytest.mark.parametrize("bagging", [False, True])
    def test_matches_serial(self, mesh8, split_batch, bagging):
        binned, vals = _data(n=4096, f=10, seed=5)
        if bagging:
            vals[::3, :] = 0.0                     # "out of bag" rows
        F, B, L = binned.shape[1], 16, 8
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(F, B, jnp.int32)
        na = jnp.full(F, -1, jnp.int32)
        fm = jnp.ones(F, bool)

        serial = make_grower(num_leaves=L, num_bins=B, params=p,
                             split_batch=split_batch)
        t_ser = serial(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na)
        dp = make_dp_grower(mesh8, num_leaves=L, num_bins=B, params=p,
                            split_batch=split_batch, owner_shard=True)
        t_dp = dp(shard_rows(mesh8, binned), shard_rows(mesh8, vals),
                  fm, nb, na)
        # F=10 over 8 shards: ceil(10/8)=2 owned histogram rows per shard
        assert dp.plan.chunk == 2 and dp.plan.fmax == 2
        assert int(t_ser.num_leaves) == int(t_dp.num_leaves) > 2
        for k in ("split_feature", "threshold_bin", "default_left",
                  "left_child", "right_child"):
            np.testing.assert_array_equal(
                np.asarray(getattr(t_ser, k)), np.asarray(getattr(t_dp, k)),
                err_msg=k)
        np.testing.assert_allclose(np.asarray(t_ser.leaf_value),
                                   np.asarray(t_dp.leaf_value),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(t_ser.leaf_of_row),
                                      np.asarray(t_dp.leaf_of_row))

    @pytest.mark.parametrize("split_batch", [1, 8])
    def test_categorical_matches_serial(self, mesh8, split_batch):
        rng = np.random.RandomState(9)
        n, f, B, L = 4096, 9, 16, 8
        binned = rng.randint(0, B, size=(n, f)).astype(np.uint8)
        # feature 4 is categorical: the label keys on category membership
        y = np.isin(binned[:, 4], [1, 5, 9]).astype(np.float32) \
            + 0.25 * rng.randn(n).astype(np.float32)
        g = (0.5 - y).astype(np.float32)
        vals = np.stack([g, np.ones(n, np.float32),
                         np.ones(n, np.float32)], axis=1)
        p = SplitParams(min_data_in_leaf=5, min_data_per_group=1,
                        cat_smooth=1.0)
        nb = jnp.full(f, B, jnp.int32)
        na = jnp.full(f, -1, jnp.int32)
        fm = jnp.ones(f, bool)
        ic = jnp.zeros(f, bool).at[4].set(True)

        serial = make_grower(num_leaves=L, num_bins=B, params=p,
                             split_batch=split_batch)
        t_ser = serial(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na,
                       is_cat=ic)
        dp = make_dp_grower(mesh8, num_leaves=L, num_bins=B, params=p,
                            split_batch=split_batch, owner_shard=True)
        t_dp = dp(shard_rows(mesh8, binned), shard_rows(mesh8, vals),
                  fm, nb, na, is_cat=ic)
        assert int(t_ser.num_leaves) == int(t_dp.num_leaves) > 2
        assert np.asarray(t_ser.is_cat_node)[:int(t_ser.num_leaves) - 1].any()
        for k in ("split_feature", "threshold_bin", "left_child",
                  "right_child", "is_cat_node", "cat_rank"):
            np.testing.assert_array_equal(
                np.asarray(getattr(t_ser, k)), np.asarray(getattr(t_dp, k)),
                err_msg=k)
        np.testing.assert_array_equal(np.asarray(t_ser.leaf_of_row),
                                      np.asarray(t_dp.leaf_of_row))

    def test_efb_group_permutation_tiebreak(self):
        """Exact-gain ties must break toward the LOWEST FEATURE ID like
        serial's flat argmax, even when EFB group order permutes shard
        ownership (lowest-shard-index would pick the wrong duplicate):
        features 0 and 2 are identical columns, but group order is
        permuted so feature 2 lives on shard 0 and feature 0 on shard 1."""
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        import lightgbm_tpu.efb as efb_mod
        mesh2 = make_mesh((2,), ("data",))
        n, B, L = 2048, 16, 4
        rng = np.random.RandomState(1)
        fcol = rng.randint(0, B, n).astype(np.uint8)
        y = (fcol >= B // 2).astype(np.float32) \
            + 0.1 * rng.randn(n).astype(np.float32)
        g = (0.5 - y).astype(np.float32)
        vals = np.stack([g, np.ones(n, np.float32),
                         np.ones(n, np.float32)], axis=1)
        # 4 singleton groups, PERMUTED: group g holds feature perm[g]
        # (feature j is in group group_of[j]); features 0 and 2 identical
        group_of = np.array([3, 2, 0, 1], np.int32)
        grouped = np.zeros((n, 4), np.uint8)
        feat_data = {0: fcol, 2: fcol,
                     1: np.zeros(n, np.uint8), 3: np.zeros(n, np.uint8)}
        for j in range(4):
            grouped[:, group_of[j]] = feat_data[j]
        efb_dev = efb_mod.EFBDevice(
            group_of_feat=jnp.asarray(group_of),
            col_idx=jnp.asarray(np.tile(
                np.arange(B, dtype=np.int32)[None], (4, 1))),
            fix0=jnp.asarray(np.zeros(4, bool)),
            off_host=np.full(4, -1, np.int32),
            group_host=group_of, group_bins=B)
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(4, B, jnp.int32)
        na = jnp.full(4, -1, jnp.int32)
        fm = jnp.ones(4, bool)
        serial = make_grower(num_leaves=L, num_bins=B, params=p,
                             efb=efb_dev)
        t_ser = serial(jnp.asarray(grouped), jnp.asarray(vals), fm, nb, na)
        dp = make_dp_grower(mesh2, num_leaves=L, num_bins=B, params=p,
                            efb=efb_dev, owner_shard=True)
        t_dp = dp(shard_rows(mesh2, grouped), shard_rows(mesh2, vals),
                  fm, nb, na)
        assert int(t_ser.num_leaves) > 1
        assert int(np.asarray(t_ser.split_feature)[0]) == 0
        np.testing.assert_array_equal(np.asarray(t_ser.split_feature),
                                      np.asarray(t_dp.split_feature))

    def test_monotone_matches_serial(self, mesh8):
        # monotone 'basic' under owner sharding: the scan sees the owned
        # slice of the constraint vector, partitioning the global one
        rng = np.random.RandomState(3)
        n, f, B, L = 4096, 10, 16, 8
        binned = rng.randint(0, B, size=(n, f)).astype(np.uint8)
        y = (binned[:, 2].astype(np.float32) / B
             + 0.3 * rng.randn(n).astype(np.float32))
        g = (0.5 - y).astype(np.float32)
        vals = np.stack([g, np.ones(n, np.float32),
                         np.ones(n, np.float32)], axis=1)
        mono = np.zeros(f, np.int32)
        mono[2] = 1
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(f, B, jnp.int32)
        na = jnp.full(f, -1, jnp.int32)
        fm = jnp.ones(f, bool)
        serial = make_grower(num_leaves=L, num_bins=B, params=p, mono=mono)
        t_ser = serial(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na)
        dp = make_dp_grower(mesh8, num_leaves=L, num_bins=B, params=p,
                            mono=mono, owner_shard=True)
        t_dp = dp(shard_rows(mesh8, binned), shard_rows(mesh8, vals),
                  fm, nb, na)
        assert int(t_ser.num_leaves) == int(t_dp.num_leaves) > 2
        np.testing.assert_array_equal(np.asarray(t_ser.split_feature),
                                      np.asarray(t_dp.split_feature))
        np.testing.assert_allclose(np.asarray(t_ser.leaf_value),
                                   np.asarray(t_dp.leaf_value),
                                   rtol=1e-4, atol=1e-5)


class TestVotingParallel:
    def test_quality_with_vote_compression(self, mesh8):
        binned, vals = _data(n=4096, f=8)
        F, B, L = binned.shape[1], 16, 8
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(F, B, jnp.int32)
        na = jnp.full(F, -1, jnp.int32)
        fm = jnp.ones(F, bool)
        vp = make_voting_grower(mesh8, num_leaves=L, num_bins=B, params=p,
                                top_k=2)
        t = vp(shard_rows(mesh8, binned), shard_rows(mesh8, vals), fm, nb, na)
        assert int(t.num_leaves) > 2
        # informative feature must still be found despite vote compression
        assert int(np.asarray(t.split_feature)[0]) == 2
        bc = np.bincount(np.asarray(t.leaf_of_row),
                         minlength=int(t.num_leaves))
        np.testing.assert_allclose(bc[:int(t.num_leaves)],
                                   np.asarray(t.leaf_count)[:int(t.num_leaves)])


class TestFeatureParallel:
    def test_matches_serial(self, mesh_feat):
        binned, vals = _data(n=2048, f=8)
        F, B, L = binned.shape[1], 16, 8
        p = SplitParams(min_data_in_leaf=5)
        nb = jnp.full(F, B, jnp.int32)
        na = jnp.full(F, -1, jnp.int32)
        fm = jnp.ones(F, bool)

        serial = make_grower(num_leaves=L, num_bins=B, params=p)
        t_ser = serial(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na)

        fp = make_fp_grower(mesh_feat, num_features=F, num_leaves=L,
                            num_bins=B, params=p)
        t_fp = fp(jnp.asarray(binned), jnp.asarray(vals), fm, nb, na, na)

        assert int(t_ser.num_leaves) == int(t_fp.num_leaves) > 2
        for k in ("split_feature", "threshold_bin", "left_child", "right_child"):
            np.testing.assert_array_equal(np.asarray(getattr(t_ser, k)),
                                          np.asarray(getattr(t_fp, k)),
                                          err_msg=k)
        np.testing.assert_allclose(np.asarray(t_ser.leaf_value),
                                   np.asarray(t_fp.leaf_value),
                                   rtol=1e-4, atol=1e-5)
