"""Serving subsystem tests (lightgbm_tpu/serve/, docs/Serving.md).

The acceptance bar of ISSUE 4: serve-path predictions — in-process AND
over HTTP, including requests split across micro-batches — must be
BYTE-IDENTICAL to ``Booster.predict`` across the objective/feature
matrix (regression / binary / multiclass, categorical features,
EFB-bundled models), and the bucketed compile cache must bound XLA
compiles to ``ceil(log2(serve_max_batch)) + 1`` per model across 100
mixed-size request batches.  Satellites: plain ``Booster.predict``
through the same cache (compile counts recorded before/after),
zero-row predict, backpressure semantics, hot reload.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.predict_device import forest_trace_count
from lightgbm_tpu.serve import (BacklogFull, MicroBatcher, ModelRegistry,
                                NoModelError, PredictorEngine, Server,
                                start_http)
from lightgbm_tpu.serve.batcher import BatcherClosed


def _data(n=700, f=6, seed=0, nan_frac=0.08, cat_col=None):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, f)
    if cat_col is not None:
        x[:, cat_col] = rs.randint(0, 10, n)
    x[rs.rand(n, f) < nan_frac] = np.nan
    if cat_col is not None:
        c = x[:, cat_col]
        x[:, cat_col] = np.where(np.isnan(c), np.nan, np.abs(c))
    return x


def _train(params, x, y, rounds=10, **kw):
    ds = lgb.Dataset(x, label=y, **kw)
    return lgb.train({"verbosity": -1, "num_leaves": 8, **params}, ds,
                     num_boost_round=rounds)


def _legacy_predict(bst, x, **kw):
    """Reference result: the pre-engine host-tree walk."""
    old = bst.config.predict_bucketed
    bst.config.predict_bucketed = False
    try:
        return bst.predict(x, **kw)
    finally:
        bst.config.predict_bucketed = old
        bst._drop_predict_cache()


def _model_matrix():
    """(tag, booster, test-row factory) across the parity matrix."""
    rs = np.random.RandomState(7)
    out = []

    x = _data(seed=1)
    y = np.where(np.isnan(x[:, 0]), 0.3, x[:, 0] + 0.5 * x[:, 1])
    out.append(("regression", _train({"objective": "regression"}, x, y),
                lambda n: _data(n, seed=11)))

    x = _data(seed=2)
    y = (np.nan_to_num(x[:, 0]) > 0).astype(np.float64)
    out.append(("binary", _train({"objective": "binary"}, x, y),
                lambda n: _data(n, seed=12)))

    x = _data(seed=3)
    y = rs.randint(0, 3, len(x)).astype(np.float64)
    out.append(("multiclass",
                _train({"objective": "multiclass", "num_class": 3}, x, y),
                lambda n: _data(n, seed=13)))

    x = _data(seed=4, cat_col=2)
    y = (np.nan_to_num(x[:, 2]) % 3 == 0).astype(np.float64)
    out.append(("categorical",
                _train({"objective": "binary"}, x, y,
                       categorical_feature=[2]),
                # unseen / negative / NaN categories included
                lambda n: np.column_stack([
                    _data(n, 5, seed=14),
                    rs.randint(-2, 15, n).astype(np.float64)])[
                        :, [0, 1, 5, 2, 3, 4]]))

    # EFB-bundled model: dense block + mutually-exclusive one-hot block
    n, n_cats = 900, 12
    dense = rs.randn(n, 3)
    cat = rs.randint(0, n_cats, n)
    onehot = np.zeros((n, n_cats))
    onehot[np.arange(n), cat] = 1.0
    x = np.column_stack([dense, onehot])
    y = (dense[:, 0] + (cat % 3 == 0) > 0.5).astype(np.float64)
    bst = _train({"objective": "binary"}, x, y)
    assert bst._model.train_set.efb is not None, "EFB did not trigger"

    def _efb_rows(nn, rs=np.random.RandomState(15), n_cats=n_cats):
        d = rs.randn(nn, 3)
        c = rs.randint(0, n_cats, nn)
        oh = np.zeros((nn, n_cats))
        oh[np.arange(nn), c] = 1.0
        return np.column_stack([d, oh])

    out.append(("efb", bst, _efb_rows))
    return out


@pytest.fixture(scope="module")
def model_matrix():
    return _model_matrix()


# ---------------------------------------------------------------------------
# serve-path parity (acceptance criterion)
# ---------------------------------------------------------------------------

class TestServeParity:
    def test_server_byte_identical_across_matrix(self, model_matrix):
        """In-process serve == Booster.predict == legacy host walk,
        byte for byte, with requests split across micro-batches."""
        for tag, bst, rows_of in model_matrix:
            xt = rows_of(157)
            ref = _legacy_predict(bst, xt)
            direct = bst.predict(xt)
            assert np.array_equal(ref, direct), tag
            assert ref.dtype == direct.dtype, tag
            srv = Server({"serve_max_batch": 32, "serve_max_wait_ms": 20.0},
                         booster=bst)
            try:
                # uneven request sizes force coalescing AND splitting
                # across several micro-batches (32-row cap, 157 rows)
                futs = [srv.submit(xt[i:i + 13])
                        for i in range(0, len(xt), 13)]
                got = np.concatenate([f.result(30) for f in futs])
            finally:
                srv.close()
            assert np.array_equal(ref, got), tag
            assert ref.dtype == got.dtype, tag
            assert futs[0].info["model_version"] == "v1"

    def test_http_byte_identical(self, model_matrix):
        for tag, bst, rows_of in model_matrix:
            xt = rows_of(41)
            ref = np.asarray(bst.predict(xt))
            srv = Server({"serve_max_batch": 16, "serve_max_wait_ms": 1.0},
                         booster=bst)
            fe = start_http(srv, port=0)
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{fe.port}/predict",
                    data=json.dumps({"rows": xt.tolist()}).encode(),
                    headers={"Content-Type": "application/json"})
                resp = json.loads(urllib.request.urlopen(req).read())
            finally:
                fe.close()
                srv.close()
            got = np.asarray(resp["predictions"], ref.dtype)
            # JSON floats round-trip f32/f64 exactly (repr round trip)
            assert np.array_equal(ref, got), tag
            assert resp["model_version"] == "v1"
            assert resp["num_rows"] == len(xt)

    def test_iteration_slicing_parity(self):
        x = _data(400, seed=16)
        y = np.nan_to_num(x[:, 0])
        bst = _train({"objective": "regression",
                      "predict_bucketed": True}, x, y, rounds=12)
        xt = _data(30, seed=17)
        for kw in ({"start_iteration": 3}, {"num_iteration": 5},
                   {"start_iteration": 2, "num_iteration": 4},
                   {"raw_score": True, "num_iteration": 0}):
            got, ref = bst.predict(xt, **kw), _legacy_predict(bst, xt, **kw)
            assert np.array_equal(got, ref), kw
        lref = _legacy_predict(bst, xt, pred_leaf=True, start_iteration=4)
        assert np.array_equal(
            bst.predict(xt, pred_leaf=True, start_iteration=4), lref)

    def test_engine_predict_matches_booster(self, model_matrix):
        for tag, bst, rows_of in model_matrix:
            xt = rows_of(33)
            eng = PredictorEngine.from_booster(bst)
            assert np.array_equal(eng.predict(xt), bst.predict(xt)), tag
            assert np.array_equal(eng.predict(xt, raw_score=True),
                                  bst.predict(xt, raw_score=True)), tag


# ---------------------------------------------------------------------------
# bucketed compile cache (acceptance criterion + satellite 1)
# ---------------------------------------------------------------------------

class TestCompileCache:
    def test_bounded_compiles_100_mixed_batches(self):
        x = _data(500, seed=21)
        y = np.nan_to_num(x[:, 0])
        # distinctive (T, M) SoA shape: the trace counter is process-
        # wide and the shared jit would (correctly) give 0 traces for a
        # shape another test already compiled
        bst = _train({"objective": "regression", "num_leaves": 16},
                     x, y, rounds=14)
        max_batch = 1024
        eng = PredictorEngine.from_booster(bst, max_batch=max_batch)
        rs = np.random.RandomState(0)
        sizes = rs.randint(1, max_batch + 1, 100)
        before = forest_trace_count()
        for n in sizes:
            eng.leaf_ids(_data(int(n), seed=int(n)))
        traces = forest_trace_count() - before
        bound = int(np.ceil(np.log2(max_batch))) + 1
        assert traces <= bound, (traces, bound)
        stats = eng.compile_stats()
        assert len(stats["buckets"]) <= bound
        assert all(b & (b - 1) == 0 for b in stats["buckets"]), \
            "buckets must be powers of two"
        assert stats["max_compiles_bound"] == bound

    def test_booster_predict_stops_retracing(self):
        """Satellite 1: plain Booster.predict rides the same bucketed
        cache — compile counts recorded before/after show that varying
        row counts stop re-tracing once their buckets are warm."""
        x = _data(400, seed=22)
        y = np.nan_to_num(x[:, 1])
        bst = _train({"objective": "regression", "num_leaves": 12,
                      "predict_bucketed": True},
                     x, y, rounds=9)          # unique (T, M) shape
        warm = forest_trace_count()
        for n in (5, 100, 300):              # warm buckets 16, 128, 512
            bst.predict(_data(n, seed=n))
        warmed = forest_trace_count() - warm
        assert 1 <= warmed <= 3
        before = forest_trace_count()
        for n in (3, 7, 11, 16, 70, 90, 128, 257, 300, 400, 511, 512):
            bst.predict(_data(n, seed=n))    # all within warm buckets
        assert forest_trace_count() == before, \
            "varying row counts must not re-trace inside warm buckets"

    def test_min_bucket_floors_tiny_batches(self):
        x = _data(200, seed=23)
        bst = _train({"objective": "regression"}, x,
                     np.nan_to_num(x[:, 0]))
        eng = PredictorEngine.from_booster(bst, min_bucket=16)
        for n in (1, 2, 3, 7, 15, 16):
            eng.leaf_ids(_data(n, seed=n))
        assert list(eng.compile_stats()["buckets"]) == [16]

    def test_predict_bucketed_false_uses_host_path(self):
        x = _data(100, seed=24)
        bst = _train({"objective": "regression", "predict_bucketed":
                      False}, x, np.nan_to_num(x[:, 0]))
        assert bst.predict_engine() is None
        before = forest_trace_count()
        bst.predict(_data(10, seed=1))
        assert forest_trace_count() == before

    def test_auto_mode_engages_on_large_workloads(self):
        """predict_bucketed=auto (the default): small predicts stay on
        the host walk; once rows x trees clears the threshold the
        engine builds and serves every later call — byte-identically."""
        x = _data(600, seed=28)
        bst = _train({"objective": "regression"}, x,
                     np.nan_to_num(x[:, 0]), rounds=40)
        assert bst.config.predict_bucketed == "auto"
        assert bst.predict_engine(10) is None
        assert bst._engine_cache is None
        xt = _data(2000, seed=29)
        ref = _legacy_predict(bst, xt)
        got = bst.predict(xt)          # 2000 x 40 trees: engine engages
        assert bst._engine_cache not in (None, False)
        assert np.array_equal(ref, got)
        assert bst.predict_engine(1) is not None   # built: serves all
        small = _data(4, seed=30)
        got_small = bst.predict(small)             # rides the engine
        assert np.array_equal(got_small, _legacy_predict(bst, small))

    def test_engine_cache_invalidated_by_training(self):
        x = _data(300, seed=25)
        ds = lgb.Dataset(x, label=np.nan_to_num(x[:, 0]))
        bst = lgb.Booster(params={"objective": "regression",
                                  "predict_bucketed": True,
                                  "verbosity": -1}, train_set=ds)
        bst.update()
        e1 = bst.predict_engine()
        assert e1 is not None and len(e1.trees) == 1
        bst.update()
        e2 = bst.predict_engine()
        assert e2 is not e1 and len(e2.trees) == 2


# ---------------------------------------------------------------------------
# zero-row predict (satellite 2)
# ---------------------------------------------------------------------------

class TestZeroRow:
    def test_zero_rows_empty_result_no_device(self, model_matrix):
        for tag, bst, rows_of in model_matrix:
            k = bst._num_tree_per_iteration
            f = bst.num_feature()
            before = forest_trace_count()
            out = bst.predict(np.empty((0, f)))
            assert forest_trace_count() == before, tag
            ref = bst.predict(rows_of(3))
            assert out.shape == ((0,) if k == 1 else (0, k)), tag
            assert out.dtype == ref.dtype, tag
            leaf = bst.predict(np.empty((0, f)), pred_leaf=True)
            assert leaf.shape == (0, len(bst.trees))
            assert leaf.dtype == np.int32
            raw = bst.predict(np.empty((0, f)), raw_score=True)
            assert raw.dtype == np.float64

    def test_zero_rows_shape_check_still_applies(self):
        x = _data(100, seed=26)
        bst = _train({"objective": "regression"}, x,
                     np.nan_to_num(x[:, 0]))
        from lightgbm_tpu.basic import LightGBMError
        with pytest.raises(LightGBMError, match="predict_disable_shape_check"):
            bst.predict(np.empty((0, 3)))
        out = bst.predict(np.empty((0, 3)),
                          predict_disable_shape_check=True)
        assert out.shape == (0,)

    def test_zero_rows_through_server(self):
        x = _data(100, seed=27)
        bst = _train({"objective": "binary"}, x,
                     (np.nan_to_num(x[:, 0]) > 0).astype(float))
        srv = Server({}, booster=bst)
        try:
            out = srv.predict(np.empty((0, x.shape[1])))
        finally:
            srv.close()
        assert out.shape == (0,)


# ---------------------------------------------------------------------------
# micro-batcher
# ---------------------------------------------------------------------------

class TestBatcher:
    def test_coalesces_concurrent_requests(self):
        seen = []

        def predict_fn(rows):
            seen.append(len(rows))
            return rows[:, 0] * 2.0

        gate = MicroBatcher(predict_fn, max_batch=64, max_wait_ms=150.0,
                            queue_rows=1024)
        try:
            futs = [gate.submit(np.full((5, 2), i, float))
                    for i in range(6)]
            outs = [f.result(10) for f in futs]
        finally:
            gate.close()
        for i, o in enumerate(outs):
            assert np.array_equal(o, np.full(5, 2.0 * i))
        # the 60 ms window coalesced (sub-ms submits) into ONE batch
        assert max(seen) == 30

    def test_backpressure_rejects_with_retry_after(self):
        release = threading.Event()

        def predict_fn(rows):
            release.wait(10)
            return rows[:, 0]

        gate = MicroBatcher(predict_fn, max_batch=4, max_wait_ms=0.0,
                            queue_rows=8)
        try:
            futs = [gate.submit(np.zeros((4, 1)))]
            time.sleep(0.05)            # worker picks up batch 1, blocks
            futs += [gate.submit(np.zeros((4, 1))),
                     gate.submit(np.zeros((4, 1)))]
            with pytest.raises(BacklogFull) as ei:
                gate.submit(np.zeros((4, 1)))
            assert ei.value.retry_after_ms > 0
            assert ei.value.depth_rows == 8
            release.set()
            for f in futs:
                f.result(10)
        finally:
            release.set()
            gate.close()

    def test_transient_errors_retry_fatal_do_not(self):
        from lightgbm_tpu.utils.resilience import RetryPolicy
        calls = {"n": 0}

        def flaky(rows):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("collective timed out")  # transient
            return rows[:, 0]

        gate = MicroBatcher(flaky, max_batch=8, max_wait_ms=0.0,
                            retry_policy=RetryPolicy(max_attempts=2,
                                                     base_delay_s=0.01))
        try:
            assert gate.submit(np.ones((2, 1))).result(10) is not None
            assert calls["n"] == 2

            def fatal(rows):
                raise TypeError("broken request")

            gate.predict_fn = fatal
            with pytest.raises(TypeError):
                gate.submit(np.ones((2, 1))).result(10)
        finally:
            gate.close()

    def test_close_drains_queue_then_rejects_new(self):
        hold = threading.Event()
        gate = MicroBatcher(lambda r: (hold.wait(5), r[:, 0])[1],
                            max_batch=2, max_wait_ms=0.0)
        f1 = gate.submit(np.zeros((2, 1)))
        time.sleep(0.05)
        f2 = gate.submit(np.zeros((2, 1)))   # queued behind the block
        hold.set()
        gate.close()
        f1.result(5)                 # in-flight batch completed
        f2.result(5)                 # queued work drained before exit
        with pytest.raises(BatcherClosed):
            gate.submit(np.zeros((1, 1)))

    def test_mixed_width_requests_never_kill_worker(self):
        """A wrong-width request must fail ALONE: widths never
        concatenate into one batch, and no request failure may kill the
        worker thread (which would hang every later request)."""
        gate = MicroBatcher(lambda r: r[:, 0], max_batch=64,
                            max_wait_ms=50.0)
        try:
            f_a = gate.submit(np.zeros((3, 2)))
            f_b = gate.submit(np.zeros((3, 5)))   # width change: own batch
            assert np.array_equal(f_a.result(10), np.zeros(3))
            assert np.array_equal(f_b.result(10), np.zeros(3))
            assert gate._worker.is_alive()
            # 1-D vector = one row; >2-D rejected at submit, reaching
            # only the offending caller
            assert gate.submit(np.zeros(4)).result(10).shape == (1,)
            with pytest.raises(ValueError, match="2-D"):
                gate.submit(np.zeros((1, 2, 2)))
            # a predict_fn that raises fails its batch, not the worker
            def boom(rows):
                raise RuntimeError("boom")
            gate.predict_fn = boom
            with pytest.raises(RuntimeError):
                gate.submit(np.zeros((1, 2))).result(10)
            assert gate._worker.is_alive()
            gate.predict_fn = lambda r: r[:, 0]
            assert gate.submit(np.zeros((2, 2))).result(10).shape == (2,)
        finally:
            gate.close()

    def test_metrics_recorded(self):
        from lightgbm_tpu.obs import MetricsRegistry
        m = MetricsRegistry()
        gate = MicroBatcher(lambda r: r[:, 0], max_batch=8,
                            max_wait_ms=0.0, metrics=m)
        try:
            gate.submit(np.zeros((3, 1))).result(10)
        finally:
            gate.close()
        snap = m.snapshot()
        assert snap["serve.requests"]["value"] == 1
        assert snap["serve.rows"]["value"] == 3
        assert snap["serve.batch_rows"]["count"] == 1
        assert snap["serve.latency"]["count"] == 1
        occ = snap["serve.batch_occupancy"]
        assert 0 < occ["max"] <= 1.0


# ---------------------------------------------------------------------------
# registry / hot reload
# ---------------------------------------------------------------------------

class TestRegistry:
    def _boosters(self):
        x = _data(300, seed=30)
        y = np.nan_to_num(x[:, 0])
        b1 = _train({"objective": "regression"}, x, y, rounds=5)
        b2 = _train({"objective": "regression", "learning_rate": 0.3},
                    x, y, rounds=9)
        return b1, b2

    def test_atomic_swap_old_handle_survives(self):
        b1, b2 = self._boosters()
        reg = ModelRegistry()
        v1 = reg.load(booster=b1)
        old = reg.current()
        v2 = reg.load(model_str=b2.model_to_string())
        assert (v1, v2) == ("v1", "v2")
        assert reg.current().version == "v2"
        # the handle resolved BEFORE the swap still serves the old model
        xt = _data(20, seed=31)
        assert np.array_equal(old.booster.predict(xt), b1.predict(xt))
        assert len(reg.current().booster.trees) == 9

    def test_unload_guards_current(self):
        b1, b2 = self._boosters()
        reg = ModelRegistry()
        reg.load(booster=b1)
        reg.load(booster=b2)
        with pytest.raises(ValueError, match="current"):
            reg.unload("v2")
        reg.activate("v1")
        reg.unload("v2")
        assert [v["version"] for v in reg.versions()] == ["v1"]
        with pytest.raises(KeyError):
            reg.get("v2")

    def test_no_model_error(self):
        with pytest.raises(NoModelError):
            ModelRegistry().current()

    def test_load_snapshot_complete_only(self, tmp_path):
        x = _data(300, seed=32)
        y = np.nan_to_num(x[:, 0])
        out = str(tmp_path / "model.txt")
        bst = lgb.train({"objective": "regression", "verbosity": -1,
                         "output_model": out, "snapshot_freq": 2,
                         "snapshot_keep": 0},
                        lgb.Dataset(x, label=y), num_boost_round=6)
        # newest snapshot made incomplete: manifest missing == the
        # mid-write crash window; the registry must fall back
        import glob as _glob
        snaps = sorted(_glob.glob(out + ".snapshot_iter_*"))
        snaps = [s for s in snaps if s.endswith("6")]
        assert snaps
        import os
        os.unlink(snaps[0] + ".manifest.json")
        reg = ModelRegistry()
        v = reg.load_snapshot(out)
        assert "snapshot iter 4" in reg.get(v).source
        xt = _data(10, seed=33)
        assert np.array_equal(
            reg.get(v).booster.predict(xt),
            bst.predict(xt, num_iteration=4))

    def test_server_reload_switches_new_requests(self):
        b1, b2 = self._boosters()
        srv = Server({"serve_max_wait_ms": 0.0}, booster=b1)
        try:
            xt = _data(15, seed=34)
            f1 = srv.submit(xt)
            assert np.array_equal(f1.result(10), b1.predict(xt))
            assert f1.info["model_version"] == "v1"
            v2 = srv.reload(booster=b2)
            f2 = srv.submit(xt)
            assert np.array_equal(f2.result(10), b2.predict(xt))
            assert f2.info["model_version"] == v2 == "v2"
            assert srv.health()["model"]["version"] == "v2"
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# HTTP frontend
# ---------------------------------------------------------------------------

class TestHttp:
    @pytest.fixture()
    def served(self):
        x = _data(300, seed=40)
        y = (np.nan_to_num(x[:, 0]) > 0).astype(float)
        bst = _train({"objective": "binary"}, x, y)
        srv = Server({"serve_max_wait_ms": 1.0}, booster=bst)
        fe = start_http(srv, port=0)
        yield bst, srv, f"http://127.0.0.1:{fe.port}"
        fe.close()
        srv.close()

    def _post(self, url, payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req).read())

    def test_healthz_and_metrics(self, served):
        bst, srv, base = served
        self._post(base + "/predict", {"rows": _data(8, seed=41).tolist()})
        h = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert h["status"] == "ok"
        assert h["model"]["num_trees"] == len(bst.trees)
        assert h["versions"][0]["current"] is True
        m = json.loads(urllib.request.urlopen(base + "/metrics").read())
        assert m["serve.requests"]["value"] >= 1
        assert m["serve.latency_quantiles"]["p99_s"] > 0
        eng = m["serve.engine"]
        assert eng["buckets"] and eng["max_compiles_bound"] >= 1

    def test_bad_requests(self, served):
        _, _, base = served
        for payload, frag in [({}, "missing 'rows'"),
                              ({"rows": [[[1]]]}, "bad rows")]:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(base + "/predict", payload)
            assert ei.value.code == 400
            assert frag in json.loads(ei.value.read())["error"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope")
        assert ei.value.code == 404
        # wrong feature count: the model's shape check fails THIS
        # request as a 400 (never 500, never another request's batch)
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(base + "/predict", {"rows": [[1.0, 2.0]]})
        assert ei.value.code == 400
        assert "predict_disable_shape_check" in \
            json.loads(ei.value.read())["error"]
        # ...and the server still answers afterwards
        ok = self._post(base + "/predict",
                        {"rows": _data(2, seed=46).tolist()})
        assert ok["num_rows"] == 2

    def test_http_429_backpressure(self):
        x = _data(200, seed=42)
        bst = _train({"objective": "regression"}, x,
                     np.nan_to_num(x[:, 0]))
        srv = Server({"serve_max_batch": 4, "serve_max_wait_ms": 0.0,
                      "serve_queue_rows": 8}, booster=bst)
        # wedge the worker so the bounded queue fills
        hold = threading.Event()
        real = srv._predict_batch

        def slow(rows):
            hold.wait(10)
            return real(rows)

        srv.batcher.predict_fn = slow
        fe = start_http(srv, port=0)
        base = f"http://127.0.0.1:{fe.port}"
        try:
            rows = _data(4, seed=43).tolist()
            futs = [srv.submit(np.asarray(rows))]
            time.sleep(0.1)          # worker picks batch 1 and wedges
            futs += [srv.submit(np.asarray(rows)) for _ in range(2)]
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(base + "/predict", {"rows": rows})
            assert ei.value.code == 429
            assert ei.value.headers["Retry-After"]
            assert json.loads(ei.value.read())["retry_after_ms"] > 0
        finally:
            hold.set()
            for f in futs:
                f.result(10)
            fe.close()
            srv.close()

    def test_http_reload(self, served, tmp_path):
        bst, srv, base = served
        x = _data(300, seed=44)
        y = (np.nan_to_num(x[:, 1]) > 0).astype(float)
        b2 = _train({"objective": "binary", "learning_rate": 0.2}, x, y)
        path = str(tmp_path / "m2.txt")
        b2.save_model(path)
        resp = self._post(base + "/reload", {"model_file": path})
        assert resp["model_version"] == "v2"
        xt = _data(9, seed=45)
        got = self._post(base + "/predict", {"rows": xt.tolist()})
        assert got["model_version"] == "v2"
        assert np.array_equal(
            np.asarray(got["predictions"], np.float32),
            np.asarray(b2.predict(xt), np.float32))


# ---------------------------------------------------------------------------
# CLI + config surface
# ---------------------------------------------------------------------------

class TestCliAndConfig:
    def test_bare_serve_token_maps_to_task(self):
        from lightgbm_tpu.cli import _load_params
        p = _load_params(["serve", "input_model=m.txt",
                          "serve_port=1234"])
        assert p["task"] == "serve"
        assert p["input_model"] == "m.txt"
        assert p["serve_port"] == "1234"

    def test_serve_params_accepted_and_clamped(self):
        from lightgbm_tpu.config import Config
        cfg = Config({"serve_max_batch": 64, "serve_min_bucket": 256,
                      "serve_queue_rows": 1})
        assert cfg.serve_min_bucket == 64     # clamped to the batch cap
        assert cfg.serve_queue_rows == 64     # holds >= one full batch
        with pytest.raises(ValueError):
            Config({"serve_max_batch": 0})
        with pytest.raises(ValueError):
            Config({"serve_max_wait_ms": -1})
        assert Config({}).predict_bucketed == "auto"
        assert Config({"predict_bucketed": True}).predict_bucketed \
            == "true"
        with pytest.raises(ValueError):
            Config({"predict_bucketed": "sometimes"})

    def test_histogram_quantile(self):
        from lightgbm_tpu.obs.metrics import Histogram
        h = Histogram(buckets=(1.0, 2.0, 4.0))
        assert h.quantile(0.5) is None
        for v in (0.5, 1.5, 1.5, 3.0, 8.0):
            h.observe(v)
        q50 = h.quantile(0.5)
        assert 1.0 <= q50 <= 2.0
        assert h.quantile(0.0) <= h.quantile(0.5) <= h.quantile(1.0)
        assert h.quantile(1.0) == 8.0

    def test_engine_unsupported_falls_back(self):
        """A hand-built model mixing NaN-routing and NaN-converting
        nodes on one feature is refused by the engine; Booster.predict
        silently falls back to the host walk."""
        x = _data(200, seed=50, nan_frac=0.3)
        y = np.nan_to_num(x[:, 0])
        b1 = _train({"objective": "regression"}, x, y, rounds=3)
        x2 = _data(200, seed=51, nan_frac=0.0)
        b2 = _train({"objective": "regression"}, x2,
                    x2[:, 0], rounds=3)
        from lightgbm_tpu.serve.engine import EngineUnsupported
        b2._merge_from(b1)
        feats = {int(f) for t in b1.trees for f in t.split_feature}
        feats &= {int(f) for t in b2.trees[len(b1.trees):]
                  for f in t.split_feature}
        if not feats:
            pytest.skip("no shared split feature between the two models")
        miss = set()
        for t in b2.trees:
            for i in range(t.num_nodes()):
                if int(t.split_feature[i]) in feats:
                    miss.add((int(t.decision_type[i]) >> 2) & 3)
        if not (2 in miss and (miss - {2})):
            pytest.skip("merge did not produce mixed missing types")
        b2.config.predict_bucketed = "true"
        assert b2.predict_engine() is None
        with pytest.raises(EngineUnsupported):
            PredictorEngine.from_booster(b2)
        xt = _data(10, seed=52)
        assert np.array_equal(b2.predict(xt),
                              _legacy_predict(b2, xt))

    def test_device_binning_mode_close_but_opt_in(self):
        """serve_device_binning: on-device f32 binning is approximate on
        threshold ties — results must still agree on clearly-separated
        values."""
        rs = np.random.RandomState(60)
        x = rs.randint(0, 20, (400, 4)).astype(np.float64)
        y = (x[:, 0] > 10).astype(np.float64)
        bst = _train({"objective": "binary"}, x, y)
        eng = PredictorEngine.from_booster(bst)
        xt = rs.randint(0, 20, (50, 4)).astype(np.float64) + 0.25
        exact = eng.predict(xt)
        approx = eng.predict(xt, device_binning=True)
        assert np.array_equal(exact, approx)
