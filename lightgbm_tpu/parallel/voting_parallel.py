"""Voting-parallel learner: communication-compressed data parallelism.

TPU-native redesign of the reference VotingParallelTreeLearner (PV-tree,
/root/reference/src/treelearner/voting_parallel_tree_learner.cpp:15-507):
rows are sharded like data-parallel, but instead of reducing histograms for
ALL features, each shard votes its local top-k features (by local split
gain), the global vote selects the top-2k (``GlobalVoting``,
voting_parallel_tree_learner.cpp:150-181), and only those features'
histograms cross the interconnect.

Implementation: the psum hook zeroes non-voted features before reducing —
a zero histogram can never produce a valid split (count constraints), so
no separate search mask is needed.  Because the voted feature set changes
per split, the subtraction trick is disabled (both children constructed),
matching the reference's CopyLocalHistogram behavior of syncing both.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..grower import TreeArrays, make_grower
from ..obs.comm import CommLedger
from ..ops.split import SplitParams
from ..utils.jax_compat import shard_map


def _local_feature_gains(h: jax.Array, params: SplitParams,
                         n_shards: int) -> jax.Array:
    """Per-feature best LOCAL split gain from a local histogram [F, B, 3]
    — the vote statistic.  Matches the reference's local search setup:
    L1/L2-regularized gains with the per-rank constraint rescale
    ``min_data_in_leaf /= num_machines`` / ``min_sum_hessian_in_leaf /=
    num_machines`` (voting_parallel_tree_learner.cpp:61-63 — a shard
    only sees ~1/M of any leaf's rows, so unscaled constraints would
    veto splits the GLOBAL histogram easily clears)."""
    md = max(float(params.min_data_in_leaf) / n_shards, 1.0) - 0.5
    mh = float(params.min_sum_hessian_in_leaf) / n_shards
    l1, l2 = float(params.lambda_l1), float(params.lambda_l2)
    eps = 1e-10
    cum = jnp.cumsum(h, axis=1)
    total = cum[:, -1:, :]
    gl, hl = cum[..., 0], cum[..., 1]
    gr = total[..., 0] - cum[..., 0]
    hr = total[..., 1] - cum[..., 1]
    cl, cr = cum[..., 2], total[..., 2] - cum[..., 2]

    def tl1(g):
        if l1 <= 0.0:
            return g
        return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)

    gains = (tl1(gl) ** 2 / (hl + l2 + eps)
             + tl1(gr) ** 2 / (hr + l2 + eps))
    valid = (cl >= md) & (cr >= md) & (hl >= mh) & (hr >= mh)
    gains = jnp.where(valid, gains, -jnp.inf)
    return jnp.max(gains, axis=1)                       # [F]


def make_voting_grower(mesh: Mesh, *, num_leaves: int, num_bins: int,
                       params: SplitParams, top_k: int = 20,
                       max_depth: int = -1, block_rows: int = 0,
                       axis: str = "data"):
    """Jitted voting-parallel ``grow_tree`` over ``mesh`` (rows sharded)."""

    n_shards = mesh.shape[axis]
    ledger = CommLedger(n_shards)     # static comm-bytes sites (obs/comm)

    def vote_reduce(h):
        f = h.shape[0]
        k = min(top_k, f)
        gains = _local_feature_gains(h, params, n_shards)
        _, local_top = lax.top_k(gains, k)              # [k]
        onehot = jnp.zeros(f, jnp.float32).at[local_top].add(1.0)
        votes = ledger.psum(onehot, axis,
                            site="voting.votes")        # [F] vote counts
        # global top-2k by votes (ties: summed local gains)
        gain_sum = ledger.psum(jnp.where(jnp.isfinite(gains), gains, 0.0),
                               axis, site="voting.gains")
        score = votes * 1e12 + gain_sum
        k2 = min(2 * k, f)
        _, selected = lax.top_k(score, k2)
        sel_mask = jnp.zeros(f, bool).at[selected].set(True)
        # the ledger records the full zero-masked [F, B, 3] payload —
        # the tensor XLA actually reduces; the reference's
        # CopyLocalHistogram would ship only the voted k2/F slice
        return ledger.psum(h * sel_mask[:, None, None], axis,
                           site="voting.hist")

    inner = make_grower(
        num_leaves=num_leaves, num_bins=num_bins, params=params,
        max_depth=max_depth, block_rows=block_rows,
        hist_reduce=vote_reduce, subtract=False,
        # root totals must NOT come through the vote-filtered histogram
        sum_reduce=lambda t: ledger.psum(t, axis, site="voting.root_sum",
                                         cadence="tree"),
        jit=False)

    out_specs = TreeArrays(
        num_leaves=P(), split_feature=P(), threshold_bin=P(),
        default_left=P(), left_child=P(), right_child=P(), split_gain=P(),
        leaf_value=P(), leaf_weight=P(), leaf_count=P(), internal_value=P(),
        internal_weight=P(), internal_count=P(), leaf_depth=P(),
        leaf_of_row=P(axis), is_cat_node=P(), cat_rank=P(), n_steps=P())

    f = shard_map(
        inner, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P(), P(), P(), P()),
        out_specs=out_specs, check_vma=False)

    jitted = jax.jit(f)

    def grow(binned, vals, feature_mask, num_bin, na_bin, is_cat=None):
        if is_cat is None:
            is_cat = jnp.zeros(num_bin.shape[0], bool)
        return jitted(binned, vals, feature_mask, num_bin, na_bin, na_bin,
                      is_cat)

    grow.comm = ledger
    return grow
