"""Benchmark: HIGGS-shaped binary classification training throughput.

Mirrors the reference's headline experiment (docs/Experiments.rst: HIGGS,
500 iterations, num_leaves=255 -> 130.094 s on 2x E5-2690v4, i.e. 3.843
iters/s; GPU docs recommend 63 bins for accelerator runs,
docs/GPU-Performance.rst:108-124).

Primary metric (round-over-round comparable): steady-state iters/s on a
1M-row slice at 31 leaves / 63 bins; ``vs_baseline`` is against the
reference's full-size 3.843 iters/s.  ``extra`` carries the baseline-shaped
points VERDICT r2 asked for: a 255-leaf run and a 10M-row scaling point.

Round-3 perf notes (PROFILE.md): training runs in fused on-device chunks
(lax.scan over whole iterations, one host sync per chunk — the tunneled
chip costs ~67 ms per blocking call), and the histogram kernel uses the
[C, rows] x [rows, F*Bp] orientation with a lane-aligned bin axis.
Round-2's bench also silently binned at 255 bins (Dataset() without
params); params are now passed to the Dataset constructor.

Robustness: the measurement runs in a CHILD process; the parent retries
with backoff on failure (shrinking timeouts — an unbounded retry ladder
can eat the round's budget, ADVICE r2), falls back to a reduced CPU run as
a last resort, and ALWAYS prints exactly one JSON line
{"metric", "value", "unit", "vs_baseline"[, "extra"][, "error"]}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_IPS = 500.0 / 130.094  # reference HIGGS CPU (Experiments.rst:113)
METRIC = "higgs1m_binary_train_iters_per_sec"
N_ROWS, N_FEAT = 1_000_000, 28
PRIMARY_LEAVES, PRIMARY_MAX_BIN = 31, 63
PRIMARY_PADDED_BIN = 64          # ops/histogram.py pads the bin axis to 64

# bf16/f32 MXU peak per chip for MFU estimate; unknown kinds report FLOP/s.
PEAK_FLOPS = {
    "v5lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v4": 275e12, "v6e": 918e12, "v6lite": 918e12,
}


def make_higgs_like(n: int, f: int, seed: int = 0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, f).astype(np.float32)
    logit = (1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.6 * x[:, 2] * x[:, 3]
             + 0.4 * np.abs(x[:, 4]) + 0.5 * rng.randn(n))
    y = (logit > 0).astype(np.float32)
    return x, y


def _train_point(lgb, x, y, num_leaves, chunk, n_chunks, tag, ds=None,
                 split_batch=0):
    """Train one config; returns (ips, auc, ds) steady-state over n_chunks
    fused chunks (or per-iter updates when fusion is unavailable).  Pass
    ``ds`` to reuse an already-binned dataset (num_leaves is a Booster
    param; binning is identical across points on the same data).
    split_batch: 0 = config auto (strict below 64 leaves, 8-way above),
    explicit K pins the grower's super-step width (grower.py)."""
    params = {
        "objective": "binary", "num_leaves": num_leaves,
        "learning_rate": 0.1, "max_bin": PRIMARY_MAX_BIN,
        "min_data_in_leaf": 20, "verbosity": 0,
        "split_batch": split_batch,
    }
    t0 = time.time()
    if ds is None:
        ds = lgb.Dataset(x, label=y, params=params)
        ds.construct()
    t_bin = time.time() - t0

    bst = lgb.Booster(params=dict(params, fused_chunk=chunk),
                      train_set=ds)
    m = bst._model
    fused = m.supports_fused() and chunk > 1

    t0 = time.time()
    if fused:
        m.train_chunk(chunk)          # includes XLA compile
    else:
        bst.update()
    np.asarray(m.score)
    t_compile = time.time() - t0

    t0 = time.time()
    start_iter = m.iter_
    if fused:
        for _ in range(n_chunks):
            if m.train_chunk(chunk):
                break                 # no-split stop: count only real iters
    else:
        for _ in range(n_chunks * chunk):
            if bst.update():
                break
    np.asarray(m.score)               # hard sync
    dt = time.time() - t0
    iters = m.iter_ - start_iter
    ips = iters / max(dt, 1e-9)

    from lightgbm_tpu.metrics import _auc
    auc = _auc(y, np.asarray(m.train_score())[:, 0], None)
    print(f"[bench] {tag}: bin={t_bin:.1f}s compile+warm={t_compile:.1f}s "
          f"steady={dt:.1f}s/{iters} iters -> {ips:.3f} iters/s "
          f"(train-AUC={auc:.4f}, fused={fused})",
          file=sys.stderr, flush=True)
    return ips, auc, ds


def child() -> None:
    """The actual measurement; prints the JSON line on success."""
    quick = os.environ.get("_BENCH_QUICK") == "1"

    print("[bench] importing jax / claiming device...", file=sys.stderr,
          flush=True)
    t_dev = time.time()
    import jax
    if os.environ.get("_BENCH_CPU") == "1":
        # in-process override, NOT the JAX_PLATFORMS env var: the axon
        # sitecustomize pins the platform config at interpreter start, so
        # the env var is ignored and jax.devices() would still try to
        # claim the (possibly wedged) TPU tunnel; jax.config.update is
        # the supported escape (same pattern as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    print(f"[bench] devices={devs} ({time.time() - t_dev:.1f}s)",
          file=sys.stderr, flush=True)
    import lightgbm_tpu as lgb

    x, y = make_higgs_like(N_ROWS, N_FEAT)

    # primary: 1M x 28, 31 leaves, 8-way batched super-steps (the
    # framework's fast growth mode; AUC reported alongside so quality is
    # auditable against the strict point below)
    ips1, auc1, ds1 = _train_point(lgb, x, y, num_leaves=PRIMARY_LEAVES,
                                   chunk=4 if quick else 25,
                                   n_chunks=1 if quick else 4,
                                   tag="1M/31leaf/sb8", split_batch=8)

    rec = {
        "metric": METRIC,
        "value": round(ips1, 3),
        "unit": ("iters/s (1M rows x 28 feat, 31 leaves, 63 bins, "
                 "split_batch=8)"),
        "vs_baseline": round(ips1 / BASELINE_IPS, 3),
    }
    # emit the primary record NOW: if an extra point wedges and the parent
    # kills this child, the partial-stdout scan still recovers the primary
    # (the parent takes the LAST matching line, so a later enriched record
    # supersedes this one)
    print(json.dumps(rec), flush=True)

    extra = {"higgs1m_31leaf_sb8_auc": round(float(auc1), 4)}
    if not quick:
        # strict leaf-wise growth (split_batch=1): round-over-round
        # comparable with BENCH_r02/r03 history + the AUC quality anchor
        try:
            ips0, auc0, _ = _train_point(lgb, x, y,
                                         num_leaves=PRIMARY_LEAVES,
                                         chunk=25, n_chunks=2,
                                         tag="1M/31leaf/strict", ds=ds1,
                                         split_batch=1)
            extra["higgs1m_31leaf_strict_iters_per_sec"] = round(ips0, 3)
            extra["higgs1m_31leaf_strict_auc"] = round(float(auc0), 4)
        except Exception as e:
            extra["higgs1m_strict_error"] = f"{type(e).__name__}: {e}"[:200]
        # VERDICT r2 task 3a: the baseline's 255-leaf shape (at 1M rows)
        try:
            ips2, auc2, _ = _train_point(lgb, x, y, num_leaves=255, chunk=4,
                                         n_chunks=2, tag="1M/255leaf",
                                         ds=ds1)
            extra["higgs1m_255leaf_iters_per_sec"] = round(ips2, 3)
            extra["higgs1m_255leaf_auc"] = round(float(auc2), 4)
        except Exception as e:       # keep the primary JSON alive
            extra["higgs1m_255leaf_error"] = f"{type(e).__name__}: {e}"[:200]
        # VERDICT r2 task 3b: 10M-row scaling point (31 leaves)
        try:
            x10 = np.concatenate([x] * 10, axis=0)
            rng = np.random.RandomState(7)
            for i in range(10):     # chunked f32 noise: no 2 GB f64 spike
                sl = slice(i * N_ROWS, (i + 1) * N_ROWS)
                x10[sl] += (rng.standard_normal(
                    (N_ROWS, N_FEAT)).astype(np.float32) * 1e-3)
            y10 = np.concatenate([y] * 10)
            ips3, auc3, _ = _train_point(lgb, x10, y10, num_leaves=31,
                                         chunk=8, n_chunks=2,
                                         tag="10M/31leaf/sb8",
                                         split_batch=8)
            extra["higgs10m_iters_per_sec"] = round(ips3, 3)
            extra["higgs10m_auc"] = round(float(auc3), 4)
        except Exception as e:
            extra["higgs10m_error"] = f"{type(e).__name__}: {e}"[:200]

    # observability: achieved histogram FLOP/s + MFU estimate for the
    # primary point (one-hot contraction, (num_leaves-1) passes/iter)
    hist_flops = (2.0 * 3 * N_ROWS * N_FEAT * PRIMARY_PADDED_BIN
                  * (PRIMARY_LEAVES - 1))
    achieved = hist_flops * ips1
    kind = devs[0].device_kind.lower().replace(" ", "")
    peak = next((v for k, v in PEAK_FLOPS.items() if k in kind), None)
    mfu = f"{achieved / peak:.1%}" if peak else "n/a"
    print(f"[bench] primary {ips1:.2f} iters/s train-AUC={auc1:.4f} "
          f"hist~{achieved / 1e12:.2f} TFLOP/s (MFU~{mfu} of "
          f"{devs[0].device_kind})", file=sys.stderr)

    if extra:
        if "higgs1m_255leaf_iters_per_sec" in extra:
            extra["higgs1m_255leaf_vs_baseline"] = round(
                extra["higgs1m_255leaf_iters_per_sec"] / BASELINE_IPS, 3)
        rec["extra"] = extra
        print(json.dumps(rec), flush=True)


def _last_metric_line(stdout: str):
    """Last (most-enriched) JSON metric line, or None."""
    found = None
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{") and METRIC in line:
            found = line
    return found


def run_child(extra_env, timeout: int):
    env = dict(os.environ, _BENCH_CHILD="1")
    env.update(extra_env)
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired as e:
        def _txt(b):
            return (b.decode(errors="replace") if isinstance(b, bytes)
                    else (b or ""))
        sys.stderr.write(_txt(e.stderr)[-2000:])
        # the child prints the primary record before the optional extra
        # points — a hang in an extra must not discard the primary
        line = _last_metric_line(_txt(e.stdout))
        if line:
            return line, None
        return None, f"timeout after {timeout}s"
    sys.stderr.write(r.stderr[-4000:] if r.stderr else "")
    line = _last_metric_line(r.stdout)
    if line:
        return line, None
    return None, f"rc={r.returncode}, no JSON line"


def main():
    if os.environ.get("_BENCH_CHILD"):
        child()
        return

    errors = []
    # shrinking timeouts (ADVICE r2: a fixed 2400s ladder could eat the
    # round's budget); later attempts drop the extra points via _BENCH_QUICK
    for attempt, (backoff, timeout, env) in enumerate((
            (0, 2400, {}),
            (20, 1200, {"_BENCH_QUICK": "1"}),
            (60, 900, {"_BENCH_QUICK": "1"}))):
        if backoff:
            print(f"[bench] retrying in {backoff}s...", file=sys.stderr,
                  flush=True)
            time.sleep(backoff)
        line, err = run_child(env, timeout=timeout)
        if line:
            print(line, flush=True)
            return
        errors.append(f"attempt{attempt + 1}: {err}")
        print(f"[bench] attempt {attempt + 1} failed: {err}", file=sys.stderr,
              flush=True)

    # last resort: reduced CPU run — an honest degraded number beats none
    line, err = run_child({"_BENCH_CPU": "1", "_BENCH_QUICK": "1"},
                          timeout=600)
    if line:
        rec = json.loads(line)
        rec["error"] = ("degraded: accelerator unavailable, CPU fallback; "
                        + "; ".join(errors))
        print(json.dumps(rec), flush=True)
        return
    errors.append(f"cpu-fallback: {err}")
    print(json.dumps({
        "metric": METRIC, "value": 0.0, "unit": "iters/s",
        "vs_baseline": 0.0, "error": "; ".join(errors)}), flush=True)


if __name__ == "__main__":
    main()
