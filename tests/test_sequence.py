"""Sequence streaming construction (basic.py:621/1574 analog): Dataset built
from batched row-access objects matches in-memory construction."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


class NumpySequence(lgb.Sequence):
    def __init__(self, arr, batch_size=512):
        self.arr = arr
        self.batch_size = batch_size

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            return self.arr[idx]
        if isinstance(idx, slice):
            return self.arr[idx]
        return self.arr[np.asarray(idx)]


    def __len__(self):
        return len(self.arr)


class RowOnlySequence(NumpySequence):
    """Only int/slice indexing — exercises the per-row fallback."""

    def __getitem__(self, idx):
        if isinstance(idx, list):
            raise TypeError("list indexing unsupported")
        return self.arr[idx]


@pytest.fixture(scope="module")
def data():
    rs = np.random.RandomState(3)
    x = rs.randn(2500, 12)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
    return x, y


PARAMS = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "min_data_in_leaf": 5, "verbosity": -1, "enable_bundle": False}


def test_single_sequence_matches_dense(data):
    x, y = data
    ds_seq = lgb.Dataset(NumpySequence(x), label=y, params=PARAMS).construct()
    ds_mem = lgb.Dataset(x, label=y, params=PARAMS).construct()
    np.testing.assert_array_equal(ds_seq.feature_binned(),
                                  ds_mem.feature_binned())


def test_sequence_list_and_training(data):
    x, y = data
    seqs = [NumpySequence(x[:1000], 256), NumpySequence(x[1000:], 999)]
    bst_seq = lgb.train(PARAMS, lgb.Dataset(seqs, label=y), num_boost_round=10)
    bst_mem = lgb.train(PARAMS, lgb.Dataset(x, label=y), num_boost_round=10)
    np.testing.assert_allclose(bst_seq.predict(x, raw_score=True),
                               bst_mem.predict(x, raw_score=True),
                               rtol=1e-5, atol=1e-5)


def test_row_only_sequence_fallback(data):
    x, y = data
    ds = lgb.Dataset(RowOnlySequence(x), label=y, params=PARAMS).construct()
    ds_mem = lgb.Dataset(x, label=y, params=PARAMS).construct()
    np.testing.assert_array_equal(ds.feature_binned(), ds_mem.feature_binned())


def test_sequence_valid_set_with_efb_reference():
    """A Sequence-built validation set against an EFB-bundled training set
    must produce the grouped binned layout (regression: it used to inherit
    ref.efb but bin per-feature)."""
    rs = np.random.RandomState(9)
    n, f = 3000, 10
    x = np.zeros((n, f))
    # mutually-exclusive one-hot columns + dense ones so EFB bundles
    cat = rs.randint(0, f - 2, size=n)
    x[np.arange(n), cat] = rs.rand(n) + 1.0
    x[:, f - 2] = rs.randn(n)
    x[:, f - 1] = rs.randn(n)
    y = (x[:, 0] + x[:, f - 2] > 0.5).astype(np.float32)
    tr = lgb.Dataset(x[:2000], label=y[:2000]).construct()
    assert tr.efb is not None and tr.efb.any_bundled
    va_seq = lgb.Dataset(NumpySequence(x[2000:]), label=y[2000:],
                         reference=tr).construct()
    va_mem = lgb.Dataset(x[2000:], label=y[2000:], reference=tr).construct()
    np.testing.assert_array_equal(va_seq.binned, va_mem.binned)
    np.testing.assert_array_equal(va_seq.feature_binned(),
                                  va_mem.feature_binned())
