"""Computation-integrity layer (lightgbm_tpu/integrity.py; ISSUE 20).

Covers the comparison primitives (ulp distance, field-by-field
TreeArrays compare, traced invariants), the seeded ``bitflip`` SDC
injection, the steady-state contracts (``integrity_check_freq=0`` adds
ZERO host syncs; ``integrity_check_freq>0`` trains byte-identical
trees), the transient-vs-sticky ladder on both the grow and score
paths, policy ``rewind`` (engine re-enters from the newest
integrity-VERIFIED snapshot) and policy ``quarantine`` (suspect ids
feed the elastic ladder's mesh-minus-suspects rung), the snapshot
finder's verified-preference, and a short SDC chaos soak
(tools/soak_train.py sdc=1)."""

import collections
import json
import os
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import integrity
from lightgbm_tpu.integrity import (IntegrityFailure, compare_tree_arrays,
                                    invariant_flags, ulp_delta)
from lightgbm_tpu.parallel import elastic
from lightgbm_tpu.utils import faultinject

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _clean_state():
    faultinject.clear()
    elastic.clear_suspects()
    integrity.reset_metrics()
    yield
    faultinject.clear()
    elastic.clear_suspects()
    integrity.reset_metrics()


def _data(n=400, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 8).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
    return x, y


BASE = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
        "deterministic": True, "seed": 3, "tpu_learner": "masked"}


def _train(extra=None, rounds=8, faults=None, n=400):
    x, y = _data(n)
    faultinject.configure(faults)
    try:
        return lgb.train(dict(BASE, **(extra or {})),
                         lgb.Dataset(x, label=y), num_boost_round=rounds)
    finally:
        faultinject.configure(None)


def _trees(bst):
    return bst.model_to_string().split("parameters:")[0] \
        .split("feature_infos")[1]


def _mvals():
    return {k: v["value"] for k, v in integrity.metrics_snapshot().items()}


# a minimal host-side stand-in for the fields the primitives touch:
# a 3-leaf tree -- node 0 splits into node 1 and leaf 0, node 1 into
# leaves 1 and 2 (child < 0 encodes leaf ~child)
_T = collections.namedtuple(
    "_T", ["num_leaves", "left_child", "right_child", "leaf_count",
           "internal_count", "split_gain", "leaf_of_row"])


def _tiny_tree(**over):
    t = _T(num_leaves=np.int32(3),
           left_child=np.array([1, ~1], np.int32),
           right_child=np.array([~0, ~2], np.int32),
           leaf_count=np.array([100., 60., 40., 0.], np.float32),
           internal_count=np.array([200., 100.], np.float32),
           split_gain=np.array([1.5, 0.25], np.float32),
           leaf_of_row=np.int32(3))
    return t._replace(**over)


# ---------------------------------------------------------------------------
# Comparison primitives
# ---------------------------------------------------------------------------

class TestPrimitives:
    def test_ulp_delta(self):
        a = np.array([1.0, 0.0, np.nan, 2.0], np.float32)
        assert ulp_delta(a, a.copy()).tolist() == [0, 0, 0, 0]
        # -0.0 == +0.0 and NaN pairs count as equal
        assert int(ulp_delta(np.float32(-0.0), np.float32(0.0)).item()) == 0
        # adjacent floats are exactly 1 ulp apart
        b = np.nextafter(a[:1], np.float32(2.0), dtype=np.float32)
        assert int(ulp_delta(a[:1], b)[0]) == 1
        # a sign flip on a non-zero value is a huge distance
        assert int(ulp_delta(np.float32(1.0),
                             np.float32(-1.0)).item()) > 2 ** 30

    def test_compare_tree_arrays(self):
        t = _tiny_tree()
        assert compare_tree_arrays(t, _tiny_tree()) == []
        # int fields compare bitwise
        div = compare_tree_arrays(
            t, _tiny_tree(left_child=np.array([1, ~2], np.int32)))
        assert [d["field"] for d in div] == ["left_child"]
        assert div[0]["index"] == 1 and div[0]["count"] == 1
        # float fields honor the ulp tolerance
        lc = t.leaf_count.copy()
        lc[0] = np.nextafter(lc[0], np.float32(1e9), dtype=np.float32)
        assert compare_tree_arrays(t, _tiny_tree(leaf_count=lc),
                                   ulp_tol=2) == []
        div = compare_tree_arrays(t, _tiny_tree(leaf_count=lc), ulp_tol=0)
        assert [d["field"] for d in div] == ["leaf_count"]
        assert div[0]["ulp"] == 1
        # the scalar leaf_of_row placeholder is never compared
        assert compare_tree_arrays(
            t, _tiny_tree(leaf_of_row=np.int32(99))) == []

    def test_invariant_flags(self):
        assert bool(invariant_flags(_tiny_tree()))
        # count conservation: node 1's children no longer sum to it
        lc = _tiny_tree().leaf_count.copy()
        lc[1] += 8.0
        assert not bool(invariant_flags(_tiny_tree(leaf_count=lc)))
        # gain finiteness over live internal nodes
        sg = np.array([np.inf, 0.25], np.float32)
        assert not bool(invariant_flags(_tiny_tree(split_gain=sg)))
        # a stump trivially passes (no live internal nodes)
        assert bool(invariant_flags(_tiny_tree(
            num_leaves=np.int32(1),
            leaf_count=np.array([200., 0., 0., 0.], np.float32))))

    def test_feature_totals_residual(self):
        import jax.numpy as jnp
        from lightgbm_tpu.ops.histogram import (compute_histogram,
                                                feature_totals_residual)
        rs = np.random.RandomState(1)
        binned = jnp.asarray(rs.randint(0, 15, (200, 4)), jnp.uint8)
        vals = jnp.asarray(rs.randn(200, 2), jnp.float32)
        hist = compute_histogram(binned, vals, num_bins=16)
        assert float(feature_totals_residual(hist, vals)) < 1e-3
        bad = hist.at[2, 3, 1].add(64.0)
        assert float(feature_totals_residual(bad, vals)) > 32.0

    def test_maybe_bitflip_deterministic_and_detectable(self):
        arr = np.linspace(1.0, 2.0, 16).astype(np.float32)
        faultinject.configure("hist_sdc:1")
        f1 = np.asarray(faultinject.maybe_bitflip("hist_sdc", arr))
        faultinject.configure("hist_sdc:1")
        f2 = np.asarray(faultinject.maybe_bitflip("hist_sdc", arr))
        # seeded: the identical corruption replays run to run
        assert f1.tobytes() == f2.tobytes()
        diff = np.nonzero(f1 != arr)[0]
        assert len(diff) == 1
        # float flips land at bit >= 8: never hidden inside ulp_tol
        assert int(ulp_delta(arr, f1).max()) >= 256
        # int operands flip exactly one bit of one element
        iv = np.arange(16, dtype=np.int32)
        faultinject.configure("hist_sdc:1")
        g = np.asarray(faultinject.maybe_bitflip("hist_sdc", iv, index=5))
        assert bin(int(g[5] ^ iv[5])).count("1") == 1
        assert np.array_equal(np.delete(g, 5), np.delete(iv, 5))
        # unarmed site: the SAME object back, no hit counted
        faultinject.configure("claim_wedge:1")
        assert faultinject.maybe_bitflip("hist_sdc", arr) is arr


# ---------------------------------------------------------------------------
# Steady state: freq=0 adds nothing; freq>0 trains identical trees
# ---------------------------------------------------------------------------

class TestSteadyState:
    def test_checked_training_is_byte_identical(self):
        ref = _trees(_train())
        for freq in (1, 3):
            assert _trees(_train({"integrity_check_freq": freq})) == ref
        m = _mvals()
        assert m["integrity.checks{path=grow}"] == 8 + 2    # freq 1 + 3
        assert "integrity.mismatches{path=grow}" not in m

    def test_freq_zero_adds_zero_host_syncs(self):
        # the acceptance pin: integrity_check_freq=0 must be the exact
        # pre-integrity training loop -- same jax.device_get count as a
        # config that never mentions integrity at all
        import jax
        x, y = _data()
        counts = []
        for extra in ({}, {"integrity_check_freq": 0}):
            dtr = lgb.Dataset(x, label=y)
            dtr.construct()
            n0 = [0]
            orig = jax.device_get

            def counting(v, n0=n0):
                n0[0] += 1
                return orig(v)

            jax.device_get = counting
            try:
                bst = lgb.train(dict(BASE, **extra), dtr,
                                num_boost_round=6)
            finally:
                jax.device_get = orig
            assert len(bst.trees) == 6
            counts.append(n0[0])
        assert counts[0] == counts[1], \
            f"integrity_check_freq=0 changed the sync count: {counts}"
        assert _mvals() == {}


# ---------------------------------------------------------------------------
# Transient vs sticky, rewind, quarantine
# ---------------------------------------------------------------------------

class TestTransientSticky:
    def test_grow_transient_absorbed_byte_identical(self):
        p = {"integrity_check_freq": 1}
        ref = _trees(_train(p))
        integrity.reset_metrics()
        got = _trees(_train(p, faults="hist_sdc:3"))
        assert got == ref
        m = _mvals()
        assert m["integrity.mismatches{path=grow}"] == 1
        assert m["integrity.transient_absorbed"] == 1
        assert "integrity.sticky" not in m

    def test_score_transient_absorbed_byte_identical(self):
        p = {"integrity_check_freq": 1}
        ref = _trees(_train(p))
        integrity.reset_metrics()
        got = _trees(_train(p, faults="score_sdc:3"))
        assert got == ref
        m = _mvals()
        assert m["integrity.mismatches{path=score}"] == 1
        assert m["integrity.transient_absorbed"] == 1

    def test_sticky_raises_classified_sdc(self):
        # fires on the check AND on the re-check: sticky under the
        # default raise policy -> IntegrityFailure, ElasticFailure
        # kind "sdc", blackbox-visible divergence summary attached
        with pytest.raises(IntegrityFailure) as ei:
            _train({"integrity_check_freq": 1}, faults="hist_sdc:3-4")
        e = ei.value
        assert elastic.failure_kind(e) == "sdc"
        assert e.iteration == 3
        assert any(d["field"] == "leaf_count" for d in e.divergences)
        m = _mvals()
        assert m["integrity.sticky"] == 1
        assert "integrity.quarantined" not in m      # raise-policy only

    def test_sticky_rewind_resumes_byte_identical(self, tmp_path):
        out = str(tmp_path / "m.txt")
        p = {"integrity_check_freq": 1, "integrity_policy": "rewind",
             "snapshot_freq": 2, "snapshot_keep": 0,
             "output_model": out}
        ref = _trees(_train(dict(p)))
        for f in os.listdir(tmp_path):
            os.unlink(tmp_path / f)
        integrity.reset_metrics()
        # hits 3+4: sticky at iteration 3 -> rewind to snapshot@2;
        # the replay's hit 5 fires once more -> transient, absorbed
        got = _trees(_train(dict(p), faults="hist_sdc:3-5"))
        assert got == ref
        m = _mvals()
        assert m["integrity.rewinds"] == 1
        assert m["integrity.sticky"] == 1
        assert m["integrity.transient_absorbed"] == 1

    def test_quarantine_policy_marks_suspects(self):
        with pytest.raises(IntegrityFailure) as ei:
            _train({"integrity_check_freq": 1,
                    "integrity_policy": "quarantine"},
                   faults="hist_sdc:3-4")
        assert ei.value.devices != ()
        assert elastic.suspected_devices() == frozenset(ei.value.devices)
        assert _mvals()["integrity.quarantined"] == 1

    def test_sdc_shrunk_drops_exactly_the_suspects(self):
        # ladder arithmetic: full mesh -> mesh-minus-suspects (not the
        # generic halving) once quarantine has named the chips
        assert elastic.sdc_shrunk(8) == 4        # no suspects: halve
        elastic.mark_suspect([5])
        assert elastic.sdc_shrunk(8) == 7
        elastic.mark_suspect([2, 6])
        assert elastic.sdc_shrunk(8) == 5
        assert elastic.sdc_shrunk(2) == 1        # floor at serial


# ---------------------------------------------------------------------------
# Snapshot integrity stamps and the verified-preference finder
# ---------------------------------------------------------------------------

class TestVerifiedSnapshots:
    def _snap_run(self, tmp_path, freq):
        out = str(tmp_path / "m.txt")
        p = dict(BASE, integrity_check_freq=freq, snapshot_freq=2,
                 snapshot_keep=0, output_model=out)
        x, y = _data()
        ds = lgb.Dataset(x, label=y)
        lgb.train(dict(p), ds, num_boost_round=8)
        from lightgbm_tpu.snapshot import params_signature
        return out, params_signature(dict(p)), lgb.Dataset(x, label=y)

    def test_freq_zero_manifests_carry_no_stamp(self, tmp_path):
        out, _, _ = self._snap_run(tmp_path, 0)
        mans = [f for f in os.listdir(tmp_path)
                if f.endswith(".manifest.json")]
        assert mans
        for f in mans:
            assert "integrity" not in json.load(open(tmp_path / f))

    def test_finder_prefers_older_verified_snapshot(self, tmp_path):
        from lightgbm_tpu.snapshot import find_latest_snapshot
        out, sig, ds = self._snap_run(tmp_path, 1)
        found = find_latest_snapshot(out, sig, ds)
        assert found is not None and found[0] == 8
        assert json.load(open(out + ".snapshot_iter_8.manifest.json")) \
            ["integrity"]["verified"] is True

        def _unverify(it):
            mp = out + f".snapshot_iter_{it}.manifest.json"
            man = json.load(open(mp))
            man["integrity"]["verified"] = False
            with open(mp, "w") as f:
                json.dump(man, f)

        # newest unverified: an older VERIFIED snapshot wins over it
        _unverify(8)
        found = find_latest_snapshot(out, sig, ds)
        assert found is not None and found[0] == 6
        # nothing verified at all: fall back to the newest valid one
        for it in (2, 4, 6):
            _unverify(it)
        found = find_latest_snapshot(out, sig, ds)
        assert found is not None and found[0] == 8


# ---------------------------------------------------------------------------
# SDC chaos soak (tools/soak_train.py sdc=1), tier-1 short variant
# ---------------------------------------------------------------------------

def test_soak_sdc_short():
    sys.path.insert(0, os.path.join(HERE, "..", "tools"))
    try:
        import soak_train
    finally:
        sys.path.pop(0)
    rep = soak_train.run_soak_train(rounds=8, n_rows=300, chaos=True,
                                    sdc=True, budget_s=240.0)
    assert rep["violations"] == [], rep
    assert rep["n_trees"] == 8
    assert rep["report"]["shrinks"] >= 1
    assert {f["kind"] for f in rep["report"]["failures"]} == {"sdc"}
    assert rep["integrity_metrics"]["integrity.sticky"] == 1
    assert rep["integrity_metrics"]["integrity.transient_absorbed"] >= 2
    assert os.path.exists(
        os.path.join(rep["workdir"], "soak_model.txt.elastic.jsonl"))
